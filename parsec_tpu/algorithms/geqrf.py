"""Tiled QR factorization (flat-tree DPLASMA dgeqrf) as a PTG taskpool.

The BASELINE.md "PTG dgeqrf reduction-tree stress" config. Task classes
mirror the classic dgeqrf JDF (panel factorization + trailing update per
step k):

    GEQRT(k):     QR of diagonal tile            → Q_k, R
    TSQRT(m,k):   QR of [R; A(m,k)] stacked      → Q₂(m,k), updated R
                  (flat reduction tree down column k: m = k+1 .. MT-1)
    UNMQR(k,n):   row-panel update A(k,n) ← Q_kᵀ·A(k,n)
    TSMQR(m,n,k): stacked-pair update [C(k,n); A(m,n)] ← Q₂(m,k)ᵀ·[..]

On completion A holds R in its upper-triangular tile blocks and zeros
below (V/T storage is a compact-BLAS artifact the functional dataflow
does not keep — see ops/tile_kernels.py). Validation identity:
AᵀA = RᵀR (orthogonal-invariant, sign-independent).

Orthogonal factors flow task→task as values (no collection placement),
so this taskpool exercises the host runtime's value-flow path; flows that
live in A carry tile placements for distribution.
"""

from __future__ import annotations

from ..dsl import ptg
from ..data.matrix import TiledMatrix
from ..ops.tile_kernels import geqrt_tile, tsmqr_tile, tsqrt_tile, unmqr_tile


def build_geqrf(A: TiledMatrix) -> ptg.Taskpool:
    """Build the GEQRF taskpool over tiled matrix ``A`` (MT ≥ NT)."""
    MT, NT = A.mt, A.nt
    if MT < NT:
        raise ValueError("GEQRF needs MT >= NT (tall or square tile grid)")
    nb = A.nb
    # Scratch collections give the orthogonal-factor flows tile
    # placements so the compiled wavefront/tile-dict executors can run
    # the DAG (values would otherwise flow only task→task); the host
    # runtime ignores them. Qs holds the (nb,nb) diagonal factors keyed
    # (k, 0); Q2s the (2nb,2nb) TSQRT factors keyed (m, k) — only the
    # strictly-below-diagonal keys actually used, so the stacked store
    # doesn't materialize (or copy per wave) the unused upper half.
    Qs = TiledMatrix(NT * nb, nb, nb, nb, name=f"{A.name}_Qs")

    class _TSQRTFactors(TiledMatrix):
        def keys(self):
            return [(m, k) for k in range(NT)
                    for m in range(k + 1, MT)]

    Q2s = _TSQRTFactors(MT * 2 * nb, NT * 2 * nb, 2 * nb, 2 * nb,
                        name=f"{A.name}_Q2s")
    Qs.scratch = Q2s.scratch = True   # intra-DAG temporaries only
    tp = ptg.Taskpool("geqrf", A=A, MT=MT, NT=NT, Qs=Qs, Q2s=Q2s)

    GEQRT = tp.task_class(
        "GEQRT", params=("k",),
        space=lambda g: ((k,) for k in range(g.NT)),
        affinity=lambda g, k: (g.A, (k, k)),
        priority=lambda g, k: 4 * (g.NT - k) ** 2,
        flows=[
            ptg.FlowSpec(
                "A", ptg.READ,
                tile=lambda g, k: (g.A, (k, k)),
                ins=[ptg.In(data=lambda g, k: (g.A, (k, k)),
                            guard=lambda g, k: k == 0),
                     ptg.In(src=("TSMQR", lambda g, k: (k, k, k - 1), "A2"),
                            guard=lambda g, k: k > 0)]),
            ptg.FlowSpec(
                "Q", ptg.WRITE,
                tile=lambda g, k: (g.Qs, (k, 0)),
                outs=[ptg.Out(dst=("UNMQR",
                               lambda g, k: [(k, n)
                                             for n in range(k + 1, g.NT)],
                               "Q"))]),
            ptg.FlowSpec(
                "R", ptg.WRITE,
                tile=lambda g, k: (g.A, (k, k)),
                outs=[ptg.Out(dst=("TSQRT", lambda g, k: (k + 1, k), "R"),
                              guard=lambda g, k: k + 1 < g.MT),
                      ptg.Out(data=lambda g, k: (g.A, (k, k)),
                              guard=lambda g, k: k + 1 >= g.MT)]),
        ])

    TSQRT = tp.task_class(
        "TSQRT", params=("m", "k"),
        space=lambda g: ((m, k) for k in range(g.NT)
                         for m in range(k + 1, g.MT)),
        affinity=lambda g, m, k: (g.A, (m, k)),
        priority=lambda g, m, k: 3 * (g.NT - k) ** 2 - m,
        flows=[
            ptg.FlowSpec(
                "R", ptg.RW,
                tile=lambda g, m, k: (g.A, (k, k)),
                ins=[ptg.In(src=("GEQRT", lambda g, m, k: (k,), "R"),
                            guard=lambda g, m, k: m == k + 1),
                     ptg.In(src=("TSQRT", lambda g, m, k: (m - 1, k), "R"),
                            guard=lambda g, m, k: m > k + 1)],
                outs=[ptg.Out(dst=("TSQRT", lambda g, m, k: (m + 1, k), "R"),
                              guard=lambda g, m, k: m + 1 < g.MT),
                      ptg.Out(data=lambda g, m, k: (g.A, (k, k)),
                              guard=lambda g, m, k: m + 1 >= g.MT)]),
            ptg.FlowSpec(
                "A", ptg.READ,
                tile=lambda g, m, k: (g.A, (m, k)),
                ins=[ptg.In(data=lambda g, m, k: (g.A, (m, k)),
                            guard=lambda g, m, k: k == 0),
                     ptg.In(src=("TSMQR", lambda g, m, k: (m, k, k - 1),
                                 "A2"),
                            guard=lambda g, m, k: k > 0)]),
            ptg.FlowSpec(
                "Q2", ptg.WRITE,
                tile=lambda g, m, k: (g.Q2s, (m, k)),
                outs=[ptg.Out(dst=("TSMQR",
                               lambda g, m, k: [(m, n, k)
                                                for n in range(k + 1, g.NT)],
                               "Q2"))]),
            # the V block of A(m,k) is consumed; R lives strictly above
            ptg.FlowSpec(
                "Z", ptg.WRITE,
                tile=lambda g, m, k: (g.A, (m, k)),
                outs=[ptg.Out(data=lambda g, m, k: (g.A, (m, k)))]),
        ])

    UNMQR = tp.task_class(
        "UNMQR", params=("k", "n"),
        space=lambda g: ((k, n) for k in range(g.NT)
                         for n in range(k + 1, g.NT)),
        affinity=lambda g, k, n: (g.A, (k, n)),
        priority=lambda g, k, n: 3 * (g.NT - k) ** 2 - n,
        flows=[
            ptg.FlowSpec(
                "Q", ptg.READ,
                tile=lambda g, k, n: (g.Qs, (k, 0)),
                ins=[ptg.In(src=("GEQRT", lambda g, k, n: (k,), "Q"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, k, n: (g.A, (k, n)),
                ins=[ptg.In(data=lambda g, k, n: (g.A, (k, n)),
                            guard=lambda g, k, n: k == 0),
                     ptg.In(src=("TSMQR", lambda g, k, n: (k, n, k - 1),
                                 "A2"),
                            guard=lambda g, k, n: k > 0)],
                outs=[ptg.Out(dst=("TSMQR",
                                   lambda g, k, n: (k + 1, n, k), "C1"),
                              guard=lambda g, k, n: k + 1 < g.MT),
                      ptg.Out(data=lambda g, k, n: (g.A, (k, n)),
                              guard=lambda g, k, n: k + 1 >= g.MT)]),
        ])

    TSMQR = tp.task_class(
        "TSMQR", params=("m", "n", "k"),
        space=lambda g: ((m, n, k) for k in range(g.NT)
                         for m in range(k + 1, g.MT)
                         for n in range(k + 1, g.NT)),
        affinity=lambda g, m, n, k: (g.A, (m, n)),
        priority=lambda g, m, n, k: (g.NT - k) ** 2 - m - n,
        flows=[
            ptg.FlowSpec(
                "Q2", ptg.READ,
                tile=lambda g, m, n, k: (g.Q2s, (m, k)),
                ins=[ptg.In(src=("TSQRT", lambda g, m, n, k: (m, k),
                                 "Q2"))]),
            # running row-k tile C(k,n), reduced down the column
            ptg.FlowSpec(
                "C1", ptg.RW,
                tile=lambda g, m, n, k: (g.A, (k, n)),
                ins=[ptg.In(src=("UNMQR", lambda g, m, n, k: (k, n), "C"),
                            guard=lambda g, m, n, k: m == k + 1),
                     ptg.In(src=("TSMQR",
                                 lambda g, m, n, k: (m - 1, n, k), "C1"),
                            guard=lambda g, m, n, k: m > k + 1)],
                outs=[ptg.Out(dst=("TSMQR",
                                   lambda g, m, n, k: (m + 1, n, k), "C1"),
                              guard=lambda g, m, n, k: m + 1 < g.MT),
                      ptg.Out(data=lambda g, m, n, k: (g.A, (k, n)),
                              guard=lambda g, m, n, k: m + 1 >= g.MT)]),
            # trailing tile A(m,n)
            ptg.FlowSpec(
                "A2", ptg.RW,
                tile=lambda g, m, n, k: (g.A, (m, n)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.A, (m, n)),
                            guard=lambda g, m, n, k: k == 0),
                     ptg.In(src=("TSMQR",
                                 lambda g, m, n, k: (m, n, k - 1), "A2"),
                            guard=lambda g, m, n, k: k > 0)],
                outs=[
                    ptg.Out(dst=("GEQRT", lambda g, m, n, k: (k + 1,), "A"),
                            guard=lambda g, m, n, k: m == k + 1 and
                            n == k + 1),
                    ptg.Out(dst=("TSQRT", lambda g, m, n, k: (m, k + 1), "A"),
                            guard=lambda g, m, n, k: m > k + 1 and
                            n == k + 1),
                    ptg.Out(dst=("UNMQR", lambda g, m, n, k: (k + 1, n), "C"),
                            guard=lambda g, m, n, k: m == k + 1 and
                            n > k + 1),
                    ptg.Out(dst=("TSMQR",
                                 lambda g, m, n, k: (m, n, k + 1), "A2"),
                            guard=lambda g, m, n, k: m > k + 1 and
                            n > k + 1),
                ]),
        ])

    @GEQRT.body
    def geqrt_body(task, A_, Qv, Rv):
        Q, R = geqrt_tile(A_)
        return {"Q": Q, "R": R}

    @TSQRT.body
    def tsqrt_body(task, R, A_, Q2v, Zv):
        import jax.numpy as jnp
        Q2, Rn = tsqrt_tile(R, A_)
        return {"R": Rn, "Q2": Q2, "Z": jnp.zeros_like(A_)}

    @UNMQR.body
    def unmqr_body(task, Q, C):
        return {"C": unmqr_tile(Q, C)}

    @TSMQR.body
    def tsmqr_body(task, Q2, C1, A2):
        nC1, nA2 = tsmqr_tile(Q2, C1, A2)
        return {"C1": nC1, "A2": nA2}

    return tp


def geqrf_flops(m: int, n: int) -> float:
    """Useful FLOPs of an m×n QR (LAPACK count, m ≥ n)."""
    return 2.0 * m * n * n - 2.0 * n ** 3 / 3.0 + m * n + n * n / 2.0


def build_geqrf_hh(A: TiledMatrix) -> ptg.Taskpool:
    """Blocked-Householder tiled QR (panel-fused flagship form).

    :func:`build_geqrf` mirrors the classic 4-kernel dgeqrf JDF, whose
    TSQRT/TSMQR recurrences serialize down each block column — the
    per-tile shape, not the MXU shape. This variant concentrates each
    step the way :func:`~.potrf.build_potrf_left` does for Cholesky:

        PANEL(k):     factor the whole block column A[k:, k] at once
                      (CholeskyQR2 + exact orthogonal-completion
                      reconstruction — ops.tile_kernels.panel_qr_tile);
                      emits the reconstruction pair (V, X⁻¹) as a
                      task→task VALUE (no collection placement)
        REDUCE(n,k):  Y_n = X⁻ᵀ·Vᵀ·A[k:, n] — the panel-wide reduction
                      for trailing block column n
        APPLY(m,n,k): A[m,n] ← A[m,n] − V_m·Y_n — rank-nb tile update
        ZEROV(m,k):   zero the reflector storage below the diagonal
                      (A holds R + zeros on completion, like build_geqrf)

    ASAP leveling yields exactly three waves per step —
    [PANEL(k)], [REDUCE(·,k)+ZEROV(·,k)], [APPLY(·,·,k)] — and the wave
    fuser lowers each to a handful of dense ops on the Aᵀ store: the
    whole trailing update is two large matmuls per step
    (Hᵀ·C = C − V·X⁻ᵀ·(Vᵀ·C)). Measured ~35× the flat-DAG tile-dict
    throughput on a v5e chip (see bench.py geqrf config).

    Distribution: PANEL/REDUCE resolve gathered column operands with
    the direct-memory pattern of reference JDF bodies — local tiles
    from the collection, remote tiles through the one-sided
    :meth:`~..comm.engine.CommEngine.fetch_tile` (CTL-gather ordering
    makes both race-free) — so the same taskpool runs single-process
    panel-fused AND multi-rank. Reference analog: the tree-reduction
    dgeqrf family (reference parsec/data_dist/matrix/reduce_col.jdf) —
    the panel here plays the whole reduction tree in one fused kernel.
    """
    MT, NT = A.mt, A.nt
    if MT < NT:
        raise ValueError("GEQRF needs MT >= NT (tall or square tile grid)")
    if A.mb != A.nb:
        raise ValueError("build_geqrf_hh needs square tiles (mb == nb)")
    nb = A.nb
    tp = ptg.Taskpool("geqrf_hh", A=A, MT=MT, NT=NT)

    PANEL = tp.task_class(
        "PANEL", params=("k",),
        space=lambda g: ((k,) for k in range(g.NT)),
        affinity=lambda g, k: (g.A, (k, k)),
        priority=lambda g, k: 3 * (g.NT - k) ** 2,
        flows=[
            # orders PANEL after every below-diagonal tile of column k
            # is written back (the direct collection reads in the body)
            ptg.FlowSpec(
                "G", ptg.CTL,
                ins=[ptg.In(src=("APPLY",
                                 lambda g, k: [(m, k, k - 1)
                                               for m in range(k + 1, g.MT)],
                                 "G"),
                            gather=True,
                            guard=lambda g, k: k > 0)]),
            ptg.FlowSpec(
                "Z", ptg.CTL,
                outs=[ptg.Out(dst=("ZEROV",
                                   lambda g, k: [(m, k)
                                                 for m in range(k + 1, g.MT)],
                                   "P"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, k: (g.A, (k, k)),
                ins=[ptg.In(data=lambda g, k: (g.A, (k, k)),
                            guard=lambda g, k: k == 0),
                     ptg.In(src=("APPLY", lambda g, k: (k, k, k - 1), "C"),
                            guard=lambda g, k: k > 0)],
                outs=[ptg.Out(data=lambda g, k: (g.A, (k, k)))]),
            # the reconstruction pair (V, X^-1): a task->task value with
            # no tile placement — the fuser carries it in state
            ptg.FlowSpec(
                "V", ptg.WRITE,
                outs=[ptg.Out(dst=("REDUCE",
                                   lambda g, k: [(n, k)
                                                 for n in range(k + 1, g.NT)],
                                   "V")),
                      ptg.Out(dst=("APPLY",
                                   lambda g, k: [(m, n, k)
                                                 for n in range(k + 1, g.NT)
                                                 for m in range(k, g.MT)],
                                   "V"))]),
        ])

    ZEROV = tp.task_class(
        "ZEROV", params=("m", "k"),
        space=lambda g: ((m, k) for k in range(g.NT)
                         for m in range(k + 1, g.MT)),
        affinity=lambda g, m, k: (g.A, (m, k)),
        priority=lambda g, m, k: 1,
        flows=[
            ptg.FlowSpec(
                "P", ptg.CTL,
                ins=[ptg.In(src=("PANEL", lambda g, m, k: (k,), "Z"))]),
            ptg.FlowSpec(
                "C", ptg.WRITE,
                tile=lambda g, m, k: (g.A, (m, k)),
                outs=[ptg.Out(data=lambda g, m, k: (g.A, (m, k)))]),
        ])

    REDUCE = tp.task_class(
        "REDUCE", params=("n", "k"),
        space=lambda g: ((n, k) for k in range(g.NT)
                         for n in range(k + 1, g.NT)),
        affinity=lambda g, n, k: (g.A, (k, n)),
        priority=lambda g, n, k: 2 * (g.NT - k) ** 2 - n,
        flows=[
            # orders REDUCE's direct column-n reads after step k-1's
            # writers of that column
            ptg.FlowSpec(
                "G", ptg.CTL,
                ins=[ptg.In(src=("APPLY",
                                 lambda g, n, k: [(m, n, k - 1)
                                                  for m in range(k, g.MT)],
                                 "G"),
                            gather=True,
                            guard=lambda g, n, k: k > 0)]),
            ptg.FlowSpec(
                "V", ptg.READ,
                ins=[ptg.In(src=("PANEL", lambda g, n, k: (k,), "V"))]),
            ptg.FlowSpec(
                "Y", ptg.WRITE,
                outs=[ptg.Out(dst=("APPLY",
                                   lambda g, n, k: [(m, n, k)
                                                    for m in range(k, g.MT)],
                                   "Y"))]),
        ])

    APPLY = tp.task_class(
        "APPLY", params=("m", "n", "k"),
        space=lambda g: ((m, n, k) for k in range(g.NT)
                         for n in range(k + 1, g.NT)
                         for m in range(k, g.MT)),
        affinity=lambda g, m, n, k: (g.A, (m, n)),
        priority=lambda g, m, n, k: (g.NT - k) ** 2 - m - n,
        flows=[
            ptg.FlowSpec(
                "V", ptg.READ,
                ins=[ptg.In(src=("PANEL", lambda g, m, n, k: (k,), "V"))]),
            ptg.FlowSpec(
                "Y", ptg.READ,
                ins=[ptg.In(src=("REDUCE", lambda g, m, n, k: (n, k),
                                 "Y"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, n, k: (g.A, (m, n)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.A, (m, n)),
                            guard=lambda g, m, n, k: k == 0),
                     ptg.In(src=("APPLY",
                                 lambda g, m, n, k: (m, n, k - 1), "C"),
                            guard=lambda g, m, n, k: k > 0)],
                outs=[
                    # unconditional write-back: the NEXT step's
                    # PANEL/REDUCE read this column straight from the
                    # collection (CTL-gather ordering)
                    ptg.Out(data=lambda g, m, n, k: (g.A, (m, n))),
                    ptg.Out(dst=("APPLY",
                                 lambda g, m, n, k: (m, n, k + 1), "C"),
                            guard=lambda g, m, n, k: k + 1 < n and
                            k + 1 <= m),
                    ptg.Out(dst=("PANEL", lambda g, m, n, k: (n,), "C"),
                            guard=lambda g, m, n, k: m == n and
                            k == n - 1),
                ]),
            ptg.FlowSpec(
                "G", ptg.CTL,
                outs=[
                    ptg.Out(dst=("PANEL", lambda g, m, n, k: (n,), "G"),
                            guard=lambda g, m, n, k: k == n - 1 and m > n),
                    ptg.Out(dst=("REDUCE",
                                 lambda g, m, n, k: (n, k + 1), "G"),
                            guard=lambda g, m, n, k: k + 1 < n and
                            m >= k + 1),
                ]),
        ])

    # the CTL-gather contract guarantees every gathered APPLY has
    # written its tile back (on its owner rank) before these bodies
    # run; local tiles read directly, remote tiles through the
    # CONCURRENT one-sided batch fetch (comm.engine.resolve_column_tiles
    # — the potrf_left pattern; same taskpool runs single-process
    # panel-fused AND multi-rank). No caching: unlike POTRF's final
    # factored columns, trailing tiles change every step.
    @PANEL.body(batchable=False)
    def panel_body(task, C, Vv):
        import numpy as np
        from ..comm.engine import resolve_column_tiles
        g = task.taskpool.g
        (k,) = task.locals
        col = [np.asarray(C, dtype=np.float32)]
        col += resolve_column_tiles(
            task, g.A, [(m, k) for m in range(k + 1, g.MT)])
        P = np.concatenate(col, axis=0)
        Qr, R = np.linalg.qr(P)                 # reduced: (mk, nb), (nb, nb)
        d = np.diagonal(Qr[:nb])
        s = np.where(d >= 0, -1.0, 1.0).astype(np.float32)
        Qr = Qr * s[None, :]
        R = R * s[:, None]
        V = Qr.copy()
        V[:nb] -= np.eye(nb, dtype=np.float32)
        X = np.eye(nb, dtype=np.float32) - Qr[:nb]
        Xinv = np.linalg.inv(X)
        dt = np.asarray(C).dtype
        return {"C": R.astype(dt), "V": (V, Xinv)}

    @ZEROV.body(batchable=False)
    def zerov_body(task, Cv):
        import numpy as np
        g = task.taskpool.g
        return {"C": np.zeros((g.A.mb, g.A.nb), dtype=g.A.dtype)}

    @REDUCE.body(batchable=False)
    def reduce_body(task, V, Yv):
        import numpy as np
        from ..comm.engine import resolve_column_tiles
        g = task.taskpool.g
        n, k = task.locals
        Vp, Xinv = V
        C = np.concatenate(
            resolve_column_tiles(
                task, g.A, [(m, n) for m in range(k, g.MT)]), axis=0)
        # Hᵀ·C = C − V·X⁻¹·(Vᵀ·C)  (H = I − V·X⁻ᵀ·Vᵀ)
        return {"Y": Xinv @ (Vp.T @ C)}

    @APPLY.body(batchable=False)
    def apply_body(task, V, Y, C):
        import numpy as np
        m, n, k = task.locals
        Vp, _Xinv = V
        nb_ = Y.shape[0]
        Vm = Vp[(m - k) * nb_:(m - k + 1) * nb_]
        out = np.asarray(C, dtype=np.float32) - Vm @ Y
        return {"C": out.astype(np.asarray(C).dtype)}

    tp.wave_fuser = _geqrf_hh_wave_fuser
    tp.requires_fuser = True     # PANEL/REDUCE bodies read the
    #                              collection directly (CTL-gather)
    return tp


def _geqrf_hh_wave_fuser(wave, geoms):
    """Lower one blocked-Householder QR wave to Aᵀ-dense ops
    (compiled.panels contract).

    Wave shapes per step k: [PANEL(k)] → panel_qr_tile on the contiguous
    panel slice, R + zeros written as one row-panel DUS, (Vᵀ, X⁻¹)
    stashed in the carry; [REDUCE(·,k)(+ZEROV(·,k))] → one tall matmul
    W = (Cᵀ·Vᵀᵀ)·X⁻¹ into the carry (the ZEROV writes were already
    folded into the panel DUS); [APPLY(·,·,k)] → Cᵀ − W·Vᵀ, one matmul
    + one trailing-slab DUS."""
    (geom,) = geoms.values()      # single-collection DAG
    import jax.numpy as jnp
    from ..ops.tile_kernels import (matmul_precision, panel_qr_tile)

    prec = matmul_precision()

    def mm(a, b):
        return jnp.matmul(a, b, preferred_element_type=jnp.float32,
                          precision=prec)

    names = sorted(g.tc.name for g in wave)
    mb, nb = geom.mb, geom.nb
    MT, NT = geom.mt, geom.nt

    if names == ["PANEL"]:
        (grp,) = wave
        if len(grp.tasks) != 1:
            return None
        (k,) = grp.tasks[0]

        def do_panel(st, k=k):
            D = st[geom.name]
            c = geom.cols(k)
            Pt = D[c, k * mb:MT * mb]
            Vt, Xinv, R = panel_qr_tile(Pt)
            st["_qr_Vt"], st["_qr_Xinv"] = Vt, Xinv
            row = jnp.concatenate(
                [R.T, jnp.zeros((nb, (MT - k - 1) * mb), R.dtype)],
                axis=1) if MT - k - 1 else R.T
            # one contiguous row-panel write: Rᵀ + the ZEROV zeros
            st[geom.name] = D.at[c, k * mb:].set(row.astype(D.dtype))
            return st

        return do_panel

    if "REDUCE" in names or names == ["ZEROV"]:
        if not set(names) <= {"REDUCE", "ZEROV"}:
            return None
        red = next((g for g in wave if g.tc.name == "REDUCE"), None)
        zer = next((g for g in wave if g.tc.name == "ZEROV"), None)
        ks = {t[-1] for g in (red, zer) if g is not None for t in g.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        if zer is not None and \
                sorted(zer.tasks) != [(m, k) for m in range(k + 1, MT)]:
            return None
        if red is None:
            return lambda st: st      # zeros already written by do_panel
        if sorted(red.tasks) != [(n, k) for n in range(k + 1, NT)]:
            return None

        def do_reduce(st, k=k):
            D = st[geom.name]
            Ct = D[(k + 1) * nb:, k * mb:MT * mb]
            W = mm(Ct, st["_qr_Vt"].T)
            st["_qr_W"] = mm(W, st["_qr_Xinv"].T)
            return st

        return do_reduce

    if names == ["APPLY"]:
        (grp,) = wave
        ks = {t[2] for t in grp.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        want = {(m, n) for n in range(k + 1, NT) for m in range(k, MT)}
        if {(m, n) for (m, n, _k) in grp.tasks} != want:
            return None

        def do_apply(st, k=k):
            D = st[geom.name]
            Ct = D[(k + 1) * nb:, k * mb:MT * mb]
            Vt = st.pop("_qr_Vt")
            W = st.pop("_qr_W")
            st.pop("_qr_Xinv", None)
            new = Ct - mm(W, Vt)
            st[geom.name] = D.at[(k + 1) * nb:, k * mb:MT * mb].set(
                new.astype(D.dtype))
            return st

        return do_apply

    return None
