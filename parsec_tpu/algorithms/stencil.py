"""1D stencil with halo exchange as a PTG taskpool.

Reference: tests/apps/stencil/stencil_1D.jdf — the canonical halo-chain
dataflow pattern (each timestep's task consumes its neighbors' previous
values), which SURVEY §5 identifies as the reference's nearest analog of
sequence/context-parallel long-context execution: the halo flows are the
ring edges, and over a multi-rank block distribution the activations
carry exactly the neighbor slices a ring-attention step would.

Radius-1 Jacobi form: ``X[t,i] = w·(X[t-1,i-1] + X[t-1,i] + X[t-1,i+1])``
with reflected (absent-neighbor-skipped) boundaries. Tiles may be scalars
or arrays — the body only needs ``+`` and ``*``.
"""

from __future__ import annotations

from ..dsl import ptg
from ..data.collection import DataCollection


def build_stencil_1d(X: DataCollection, n_tiles: int, timesteps: int,
                     weight: float = 1.0 / 3.0) -> ptg.Taskpool:
    """Stencil taskpool over collection ``X`` keyed ``(i,)`` for
    ``i in range(n_tiles)``; runs ``timesteps`` sweeps and writes the
    final values back (stencil_1D.jdf analog)."""
    tp = ptg.Taskpool("stencil1d", X=X, N=n_tiles, T=timesteps, w=weight)

    S = tp.task_class(
        "S", params=("t", "i"),
        space=lambda g: ((t, i) for t in range(g.T) for i in range(g.N)),
        affinity=lambda g, t, i: (g.X, (i,)),
        # earlier timesteps first keeps the wavefront narrow
        priority=lambda g, t, i: g.T - t,
        flows=[
            # west halo: neighbor i-1's previous value
            ptg.FlowSpec(
                "L", ptg.READ,
                tile=lambda g, t, i: (g.X, (max(i - 1, 0),)),
                ins=[ptg.In(data=lambda g, t, i: (g.X, (i - 1,)),
                            guard=lambda g, t, i: t == 0 and i > 0),
                     ptg.In(src=("S", lambda g, t, i: (t - 1, i - 1), "C"),
                            guard=lambda g, t, i: t > 0 and i > 0)]),
            # center
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, t, i: (g.X, (i,)),
                ins=[ptg.In(data=lambda g, t, i: (g.X, (i,)),
                            guard=lambda g, t, i: t == 0),
                     ptg.In(src=("S", lambda g, t, i: (t - 1, i), "C"),
                            guard=lambda g, t, i: t > 0)],
                outs=[
                    ptg.Out(dst=("S", lambda g, t, i: (t + 1, i), "C"),
                            guard=lambda g, t, i: t < g.T - 1),
                    ptg.Out(dst=("S", lambda g, t, i: (t + 1, i + 1), "L"),
                            guard=lambda g, t, i: t < g.T - 1 and
                            i + 1 < g.N),
                    ptg.Out(dst=("S", lambda g, t, i: (t + 1, i - 1), "R"),
                            guard=lambda g, t, i: t < g.T - 1 and i > 0),
                    ptg.Out(data=lambda g, t, i: (g.X, (i,)),
                            guard=lambda g, t, i: t == g.T - 1)]),
            # east halo
            ptg.FlowSpec(
                "R", ptg.READ,
                tile=lambda g, t, i: (g.X, (min(i + 1, g.N - 1),)),
                ins=[ptg.In(data=lambda g, t, i: (g.X, (i + 1,)),
                            guard=lambda g, t, i: t == 0 and i < g.N - 1),
                     ptg.In(src=("S", lambda g, t, i: (t - 1, i + 1), "C"),
                            guard=lambda g, t, i: t > 0 and i < g.N - 1)]),
        ])

    w = weight

    @S.body
    def s_body(task, L, C, R):
        # boundary tasks have no active halo dep — reflect by reusing C
        left = C if L is None else L
        right = C if R is None else R
        return (left + C + right) * w

    return tp
