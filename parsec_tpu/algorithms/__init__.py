"""Shipped task-graph algorithms (the DPLASMA-analog layer).

PTG taskpools for dense tiled linear algebra plus DTD builders — the
workloads the reference ecosystem runs on PaRSEC (dpotrf/dgemm-style) and
the BASELINE.md benchmark configs.
"""

from .potrf import build_potrf
from .gemm import build_gemm_ptg, insert_gemm_dtd
from .geqrf import build_geqrf, geqrf_flops
from .getrf import build_getrf, build_getrf_left, getrf_flops
from .stencil import build_stencil_1d
