"""Tiled LU factorization without pivoting (dgetrf_nopiv) as PTG taskpools.

Completes the DPLASMA-class dense-factorization trio next to
:mod:`~.potrf` and :mod:`~.geqrf`. The right-looking form mirrors the
classic dgetrf JDF:

    GETRF(k):     A[k,k] ← packed LU (unit-lower L, upper U)
    TRSM_U(k,n):  A[k,n] ← L[k,k]⁻¹·A[k,n]       (row panel, n > k)
    TRSM_L(m,k):  A[m,k] ← A[m,k]·U[k,k]⁻¹       (column panel, m > k)
    GEMM(m,n,k):  A[m,n] −= A[m,k]·A[k,n]

No pivoting: valid for the diagonally-dominant / well-conditioned
regime the accelerator tile-LU formulation targets (the reference
ships the same contract in its nopiv PTG examples; pivoted in-tile
fallback = ``jax.lax.linalg.lu`` at user level). On completion A holds
the packed factors (L unit-lower below the diagonal, U on/above).

:func:`build_getrf_left` is the panel-fused flagship form — the LU
analog of :func:`~.potrf.build_potrf_left`: UPDC/UPDR concentrate each
tile's updates at its step, ASAP leveling yields three waves per step
([UPDC(·,k)+UPDR(k,·)], [GETRF(k)], [TRSM_L(·,k)+TRSM_U(k,·)]), and the
wave fuser lowers each to one or two large matmuls over the Aᵀ store.
"""

from __future__ import annotations

from ..dsl import ptg
from ..data.matrix import TiledMatrix
from ..ops.tile_kernels import (gemm_tile, getrf_nopiv_tile,
                                trsm_lower_unit, trsm_upper_right)
from ..utils import compile_cache, mca_param

# Compiled-path panel-TRSM kernel for the fused LU — the POTRF
# trsm_hook ported to BOTH LU solve stages (the structural delta vs the
# Cholesky fuser: LU pays TWO triangular panel solves per step where
# POTRF pays one). "gemm" factors the diagonal tile and derives L⁻¹/U⁻¹
# in ONE matmul-rich Schur recursion (ops.lu_inv_tile), so the column
# panel (·U⁻¹, via U⁻ᵀ on the transposed store) and row panel (L⁻¹·)
# each run as one MXU matmul; it squares the factors' condition-number
# contribution, same trade as POTRF's knob. "inherit" (default) follows
# potrf.trsm_hook so existing callers that set the POTRF knob keep
# getting the coupled behavior shipped through round 5.
mca_param.register("getrf.trsm_hook", "inherit",
                   help="compiled-path panel-TRSM kernel for the fused "
                        "LU: solve (exact wide triangular solves, "
                        "reference numerics) | gemm (diagonal-inversion "
                        "MXU matmuls via lu_inv_tile; squares the "
                        "factors' condition-number contribution) | "
                        "inherit (follow potrf.trsm_hook)")
compile_cache.register_trace_knob("getrf.trsm_hook")


def _trsm_inv_mode() -> bool:
    hook = str(mca_param.get("getrf.trsm_hook", "inherit"))
    if hook == "inherit":
        hook = str(mca_param.get("potrf.trsm_hook", "solve"))
    return hook == "gemm"


def _check(A: TiledMatrix) -> int:
    if A.mt != A.nt:
        raise ValueError("GETRF needs a square tile grid")
    if A.mb != A.nb:
        raise ValueError("GETRF needs square tiles (mb == nb)")
    return A.nt


def build_getrf(A: TiledMatrix) -> ptg.Taskpool:
    """Right-looking tiled LU (the dgetrf JDF shape)."""
    NT = _check(A)
    tp = ptg.Taskpool("getrf", A=A, NT=NT)

    GETRF = tp.task_class(
        "GETRF", params=("k",),
        space=lambda g: ((k,) for k in range(g.NT)),
        affinity=lambda g, k: (g.A, (k, k)),
        priority=lambda g, k: 3 * (g.NT - k) ** 2,
        flows=[ptg.FlowSpec(
            "T", ptg.RW,
            tile=lambda g, k: (g.A, (k, k)),
            ins=[ptg.In(data=lambda g, k: (g.A, (k, k)),
                        guard=lambda g, k: k == 0),
                 ptg.In(src=("GEMM", lambda g, k: (k, k, k - 1), "C"),
                        guard=lambda g, k: k > 0)],
            outs=[ptg.Out(dst=("TRSM_L",
                               lambda g, k: [(m, k)
                                             for m in range(k + 1, g.NT)],
                               "T")),
                  ptg.Out(dst=("TRSM_U",
                               lambda g, k: [(k, n)
                                             for n in range(k + 1, g.NT)],
                               "T")),
                  ptg.Out(data=lambda g, k: (g.A, (k, k)))])])

    TRSM_L = tp.task_class(
        "TRSM_L", params=("m", "k"),
        space=lambda g: ((m, k) for k in range(g.NT)
                         for m in range(k + 1, g.NT)),
        affinity=lambda g, m, k: (g.A, (m, k)),
        priority=lambda g, m, k: 2 * (g.NT - k) ** 2 - m,
        flows=[
            ptg.FlowSpec(
                "T", ptg.READ,
                tile=lambda g, m, k: (g.A, (k, k)),
                ins=[ptg.In(src=("GETRF", lambda g, m, k: (k,), "T"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, k: (g.A, (m, k)),
                ins=[ptg.In(data=lambda g, m, k: (g.A, (m, k)),
                            guard=lambda g, m, k: k == 0),
                     ptg.In(src=("GEMM", lambda g, m, k: (m, k, k - 1),
                                 "C"),
                            guard=lambda g, m, k: k > 0)],
                outs=[ptg.Out(dst=("GEMM",
                                   lambda g, m, k: [(m, n, k)
                                                    for n in
                                                    range(k + 1, g.NT)],
                                   "L")),
                      ptg.Out(data=lambda g, m, k: (g.A, (m, k)))])])

    TRSM_U = tp.task_class(
        "TRSM_U", params=("k", "n"),
        space=lambda g: ((k, n) for k in range(g.NT)
                         for n in range(k + 1, g.NT)),
        affinity=lambda g, k, n: (g.A, (k, n)),
        priority=lambda g, k, n: 2 * (g.NT - k) ** 2 - n,
        flows=[
            ptg.FlowSpec(
                "T", ptg.READ,
                tile=lambda g, k, n: (g.A, (k, k)),
                ins=[ptg.In(src=("GETRF", lambda g, k, n: (k,), "T"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, k, n: (g.A, (k, n)),
                ins=[ptg.In(data=lambda g, k, n: (g.A, (k, n)),
                            guard=lambda g, k, n: k == 0),
                     ptg.In(src=("GEMM", lambda g, k, n: (k, n, k - 1),
                                 "C"),
                            guard=lambda g, k, n: k > 0)],
                outs=[ptg.Out(dst=("GEMM",
                                   lambda g, k, n: [(m, n, k)
                                                    for m in
                                                    range(k + 1, g.NT)],
                                   "U")),
                      ptg.Out(data=lambda g, k, n: (g.A, (k, n)))])])

    GEMM = tp.task_class(
        "GEMM", params=("m", "n", "k"),
        space=lambda g: ((m, n, k) for k in range(g.NT)
                         for m in range(k + 1, g.NT)
                         for n in range(k + 1, g.NT)),
        affinity=lambda g, m, n, k: (g.A, (m, n)),
        priority=lambda g, m, n, k: (g.NT - k) ** 2 - m - n,
        flows=[
            ptg.FlowSpec(
                "L", ptg.READ,
                tile=lambda g, m, n, k: (g.A, (m, k)),
                ins=[ptg.In(src=("TRSM_L", lambda g, m, n, k: (m, k),
                                 "C"))]),
            ptg.FlowSpec(
                "U", ptg.READ,
                tile=lambda g, m, n, k: (g.A, (k, n)),
                ins=[ptg.In(src=("TRSM_U", lambda g, m, n, k: (k, n),
                                 "C"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, n, k: (g.A, (m, n)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.A, (m, n)),
                            guard=lambda g, m, n, k: k == 0),
                     ptg.In(src=("GEMM",
                                 lambda g, m, n, k: (m, n, k - 1), "C"),
                            guard=lambda g, m, n, k: k > 0)],
                outs=[
                    ptg.Out(dst=("GEMM",
                                 lambda g, m, n, k: (m, n, k + 1), "C"),
                            guard=lambda g, m, n, k:
                            k + 1 < min(m, n)),
                    ptg.Out(dst=("GETRF", lambda g, m, n, k: (k + 1,),
                                 "T"),
                            guard=lambda g, m, n, k: m == k + 1 and
                            n == k + 1),
                    ptg.Out(dst=("TRSM_L", lambda g, m, n, k: (m, k + 1),
                                 "C"),
                            guard=lambda g, m, n, k: n == k + 1 and
                            m > k + 1),
                    ptg.Out(dst=("TRSM_U", lambda g, m, n, k: (k + 1, n),
                                 "C"),
                            guard=lambda g, m, n, k: m == k + 1 and
                            n > k + 1),
                ])])

    @GETRF.body
    def getrf_body(task, T):
        return getrf_nopiv_tile(T)

    @TRSM_L.body
    def trsm_l_body(task, T, C):
        return {"C": trsm_upper_right(T, C)}

    @TRSM_U.body
    def trsm_u_body(task, T, C):
        return {"C": trsm_lower_unit(T, C)}

    @GEMM.body
    def gemm_body(task, L, U, C):
        return gemm_tile(C, L, U, alpha=-1.0, beta=1.0)

    return tp


def build_getrf_left(A: TiledMatrix) -> ptg.Taskpool:
    """Left-looking tiled LU — the panel-fused flagship form (the
    :func:`~.potrf.build_potrf_left` analog). Each column-panel tile
    (UPDC) and row-panel tile (UPDR) receives ALL its k' < k
    contributions in one task that CTL-gathers its producer TRSMs and
    resolves their tiles with the direct-memory gathered-operand
    pattern (local reads / one-sided batched fetches) — the same
    taskpool runs single-process panel-fused AND multi-rank."""
    NT = _check(A)
    tp = ptg.Taskpool("getrf_left", A=A, NT=NT)

    # producers gathered by UPDC(m, k): column k's operands L[m, j<k]
    # and U[j<k, k]; by UPDR(k, n): L[k, j<k] and U[j<k, n]
    UPDC = tp.task_class(
        "UPDC", params=("m", "k"),
        space=lambda g: ((m, k) for k in range(1, g.NT)
                         for m in range(k, g.NT)),
        affinity=lambda g, m, k: (g.A, (m, k)),
        priority=lambda g, m, k: 2 * (g.NT - k) ** 2 - m + 1,
        flows=[
            ptg.FlowSpec(
                "GL", ptg.CTL,
                ins=[ptg.In(src=("TRSM_L",
                                 lambda g, m, k: [(m, j)
                                                  for j in range(k)],
                                 "G"),
                            gather=True)]),
            ptg.FlowSpec(
                "GU", ptg.CTL,
                ins=[ptg.In(src=("TRSM_U",
                                 lambda g, m, k: [(j, k)
                                                  for j in range(k)],
                                 "G"),
                            gather=True)]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, k: (g.A, (m, k)),
                ins=[ptg.In(data=lambda g, m, k: (g.A, (m, k)))],
                outs=[ptg.Out(dst=("GETRF", lambda g, m, k: (k,), "T"),
                              guard=lambda g, m, k: m == k),
                      ptg.Out(dst=("TRSM_L", lambda g, m, k: (m, k), "C"),
                              guard=lambda g, m, k: m > k)])])

    UPDR = tp.task_class(
        "UPDR", params=("k", "n"),
        space=lambda g: ((k, n) for k in range(1, g.NT)
                         for n in range(k + 1, g.NT)),
        affinity=lambda g, k, n: (g.A, (k, n)),
        priority=lambda g, k, n: 2 * (g.NT - k) ** 2 - n + 1,
        flows=[
            ptg.FlowSpec(
                "GL", ptg.CTL,
                ins=[ptg.In(src=("TRSM_L",
                                 lambda g, k, n: [(k, j)
                                                  for j in range(k)],
                                 "G"),
                            gather=True)]),
            ptg.FlowSpec(
                "GU", ptg.CTL,
                ins=[ptg.In(src=("TRSM_U",
                                 lambda g, k, n: [(j, n)
                                                  for j in range(k)],
                                 "G"),
                            gather=True)]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, k, n: (g.A, (k, n)),
                ins=[ptg.In(data=lambda g, k, n: (g.A, (k, n)))],
                outs=[ptg.Out(dst=("TRSM_U", lambda g, k, n: (k, n),
                                   "C"))])])

    GETRF = tp.task_class(
        "GETRF", params=("k",),
        space=lambda g: ((k,) for k in range(g.NT)),
        affinity=lambda g, k: (g.A, (k, k)),
        priority=lambda g, k: 3 * (g.NT - k) ** 2,
        flows=[ptg.FlowSpec(
            "T", ptg.RW,
            tile=lambda g, k: (g.A, (k, k)),
            ins=[ptg.In(data=lambda g, k: (g.A, (k, k)),
                        guard=lambda g, k: k == 0),
                 ptg.In(src=("UPDC", lambda g, k: (k, k), "C"),
                        guard=lambda g, k: k > 0)],
            outs=[ptg.Out(dst=("TRSM_L",
                               lambda g, k: [(m, k)
                                             for m in range(k + 1, g.NT)],
                               "T")),
                  ptg.Out(dst=("TRSM_U",
                               lambda g, k: [(k, n)
                                             for n in range(k + 1, g.NT)],
                               "T")),
                  ptg.Out(data=lambda g, k: (g.A, (k, k)))])])

    TRSM_L = tp.task_class(
        "TRSM_L", params=("m", "k"),
        space=lambda g: ((m, k) for k in range(g.NT)
                         for m in range(k + 1, g.NT)),
        affinity=lambda g, m, k: (g.A, (m, k)),
        priority=lambda g, m, k: 2 * (g.NT - k) ** 2 - m,
        flows=[
            ptg.FlowSpec(
                "T", ptg.READ,
                tile=lambda g, m, k: (g.A, (k, k)),
                ins=[ptg.In(src=("GETRF", lambda g, m, k: (k,), "T"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, k: (g.A, (m, k)),
                ins=[ptg.In(data=lambda g, m, k: (g.A, (m, k)),
                            guard=lambda g, m, k: k == 0),
                     ptg.In(src=("UPDC", lambda g, m, k: (m, k), "C"),
                            guard=lambda g, m, k: k > 0)],
                outs=[ptg.Out(data=lambda g, m, k: (g.A, (m, k)))]),
            ptg.FlowSpec(
                "G", ptg.CTL,
                outs=[ptg.Out(
                    dst=("UPDC",
                         lambda g, m, k: [(m, kk)
                                          for kk in range(k + 1,
                                                          min(m, g.NT - 1)
                                                          + 1)],
                         "GL")),
                    ptg.Out(
                    dst=("UPDR",
                         lambda g, m, k: [(m, n)
                                          for n in range(m + 1, g.NT)],
                         "GL"))])])

    TRSM_U = tp.task_class(
        "TRSM_U", params=("k", "n"),
        space=lambda g: ((k, n) for k in range(g.NT)
                         for n in range(k + 1, g.NT)),
        affinity=lambda g, k, n: (g.A, (k, n)),
        priority=lambda g, k, n: 2 * (g.NT - k) ** 2 - n,
        flows=[
            ptg.FlowSpec(
                "T", ptg.READ,
                tile=lambda g, k, n: (g.A, (k, k)),
                ins=[ptg.In(src=("GETRF", lambda g, k, n: (k,), "T"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, k, n: (g.A, (k, n)),
                ins=[ptg.In(data=lambda g, k, n: (g.A, (k, n)),
                            guard=lambda g, k, n: k == 0),
                     ptg.In(src=("UPDR", lambda g, k, n: (k, n), "C"),
                            guard=lambda g, k, n: k > 0)],
                outs=[ptg.Out(data=lambda g, k, n: (g.A, (k, n)))]),
            ptg.FlowSpec(
                "G", ptg.CTL,
                outs=[ptg.Out(
                    dst=("UPDR",
                         lambda g, k, n: [(kk, n)
                                          for kk in range(k + 1, n)],
                         "GU")),
                    ptg.Out(
                    dst=("UPDC",
                         lambda g, k, n: [(m, n)
                                          for m in range(n, g.NT)],
                         "GU"))])])

    @UPDC.body(batchable=False)
    def updc_body(task, C):
        import numpy as np
        from ..comm.engine import resolve_column_tiles
        g = task.taskpool.g
        m, k = task.locals
        Ls = resolve_column_tiles(task, g.A, [(m, j) for j in range(k)])
        Us = resolve_column_tiles(task, g.A, [(j, k) for j in range(k)])
        acc = np.asarray(C, dtype=np.float32).copy()
        for Lj, Uj in zip(Ls, Us):
            acc -= Lj @ Uj
        return acc.astype(np.asarray(C).dtype)

    @UPDR.body(batchable=False)
    def updr_body(task, C):
        import numpy as np
        from ..comm.engine import resolve_column_tiles
        g = task.taskpool.g
        k, n = task.locals
        Ls = resolve_column_tiles(task, g.A, [(k, j) for j in range(k)])
        Us = resolve_column_tiles(task, g.A, [(j, n) for j in range(k)])
        acc = np.asarray(C, dtype=np.float32).copy()
        for Lj, Uj in zip(Ls, Us):
            acc -= Lj @ Uj
        return acc.astype(np.asarray(C).dtype)

    @GETRF.body
    def getrf_body(task, T):
        return getrf_nopiv_tile(T)

    @TRSM_L.body(batchable=False)
    def trsm_l_body(task, T, C):
        return {"C": trsm_upper_right(T, C)}

    @TRSM_U.body(batchable=False)
    def trsm_u_body(task, T, C):
        return {"C": trsm_lower_unit(T, C)}

    tp.wave_fuser = _getrf_left_wave_fuser
    tp.requires_fuser = True     # UPDC/UPDR bodies resolve gathered
    #                              operands outside per-tile flows
    return tp


def _getrf_left_wave_fuser(wave, geoms):
    """Lower one left-looking LU wave to Aᵀ-dense ops (compiled.panels
    contract). Wave shapes per step k:
    [UPDC(·,k)+UPDR(k,·)] → two large matmuls into the carry;
    [GETRF(k)] → in-tile packed LU (Schur recursion);
    [TRSM_L(·,k)+TRSM_U(k,·)] → two triangular applies + two DUS.

    Storage: TWO stores, each with a SINGLE row-panel DUS chain —
    the L/diag panels land in the collection's Aᵀ store
    (write [c, k·mb:], exactly POTRF's shape) and the U row panels in
    an A-layout carry ``st["_us"]`` (write [k·nb:(k+1)·nb, (k+1)·mb:]).
    Interleaving both chains on ONE array defeats XLA's in-place DUS
    scheduling and costs a full store copy per step — measured 7 ms/step
    (= 168 ms of the 314 ms round-3 total) at N=24576 on a v5e; the
    two-store split is ~0 ms/step. The final GETRF wave merges the U
    store back with one transpose+select (us.T lands exactly on the
    Aᵀ-store's U-tile region), so the executor's output contract (one
    packed-LU array per collection) is unchanged.

    Round-5 structure findings (N=32768, NB=1024, captured):
    row panels are produced in A-LAYOUT (see do_update) so no
    two-large-dims transpose appears in the graph; measured floor with
    the sequential in-tile kernels stubbed is ~65 TF/s (run 0.358 s),
    of which ~147 ms is slice/DUS/merge structure — the matmuls run at
    ~73% MXU efficiency on their share. Variants measured SLOWER and
    reverted: rank-2 base elimination (tile_kernels._lu_base note),
    splitting the concat into two DUS writes (54.7 vs 56.9-59.7),
    lax.dot_general axis-0 contractions (46.0).

    Round-6 rework (getrf.trsm_hook=gemm): the sequential per-step tail
    was the in-tile LU (two triangular solves per recursion level) PLUS
    two standalone nb-sized tri_inv_tile recursions in the TRSM wave.
    ``lu_inv_tile`` folds all three into one Schur recursion whose
    panel solves are matmuls against the child inverses — triangular
    solves survive only at the ≤64 base case — and the GETRF wave
    stashes L⁻¹/U⁻¹ in the carry so the TRSM wave is two pure MXU
    matmuls (exactly POTRF's stash-the-inverse shape)."""
    (geom,) = geoms.values()
    import jax
    import jax.numpy as jnp
    from ..ops.tile_kernels import (getrf_nopiv_tile, lu_inv_tile,
                                    lu_split, matmul_precision,
                                    tri_inv_tile)

    prec = matmul_precision()

    def mm(a, b):
        return jnp.matmul(a, b, preferred_element_type=jnp.float32,
                          precision=prec)

    names = sorted(g.tc.name for g in wave)
    mb, nb = geom.mb, geom.nb
    MT, NT = geom.mt, geom.nt
    inv_mode = _trsm_inv_mode()

    if names in (["UPDC"], ["UPDC", "UPDR"]):
        updc = next(g for g in wave if g.tc.name == "UPDC")
        ks = {t[1] for t in updc.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        if sorted(updc.tasks) != [(m, k) for m in range(k, MT)]:
            return None
        updr = next((g for g in wave if g.tc.name == "UPDR"), None)
        if updr is not None and sorted(updr.tasks) != \
                [(k, n) for n in range(k + 1, NT)]:
            return None

        def do_update(st, k=k):
            D = st[geom.name]
            us = st["_us"]       # exists: TRSM(0) precedes every update
            r0 = k * nb
            # column panel (Aᵀ rows = block-col k): Uᵀ[:k,k]·Lᵀ[k:,:k];
            # U factors read from the A-layout U store (transpose folds
            # into the dot), L factors from the Aᵀ collection store
            Ut = us[0:k * nb, k * mb:(k + 1) * mb].T   # (nb, k*nb)
            Lt = D[0:k * nb, k * mb:]             # (k*nb, mk)
            st["_lu_col"] = D[r0:r0 + nb, k * mb:] - mm(Ut, Lt)
            if k + 1 < NT:
                # row panel in A-LAYOUT (nb, T): A[k,j>k] - L[k,:k]·U[:k,j>k].
                # Round-4 computed it Aᵀ-oriented via us[...].T with BOTH
                # dims large — XLA materializes that transpose, ~1 GB-
                # class copies per step (~46 GB over the run, measured
                # +55 ms). A-layout needs only (x, nb) transposes (the
                # L row read and the residual base, ≤130 MB each) and
                # reads the U store straight.
                Lrow = D[0:k * nb, k * mb:(k + 1) * mb].T    # (nb, k*nb)
                Ublk = us[0:k * nb, (k + 1) * mb:]           # (k*nb, T)
                baseA = D[(k + 1) * nb:, k * mb:(k + 1) * mb].T  # (nb, T)
                st["_lu_rowA"] = baseA - mm(Lrow, Ublk)
            return st

        return do_update

    if names == ["GETRF"]:
        (grp,) = wave
        if len(grp.tasks) != 1:
            return None
        (k,) = grp.tasks[0]

        def do_getrf(st, k=k, last=(k == NT - 1)):
            D = st[geom.name]
            c = slice(k * nb, (k + 1) * nb)
            colk = st.pop("_lu_col", None)
            diag = colk[:, :nb].T if colk is not None \
                else D[c, k * mb:(k + 1) * mb].T
            if inv_mode and not last:
                # factor + both inverses in ONE matmul-rich recursion;
                # the TRSM wave consumes the stashed inverses as plain
                # matmuls (POTRF's _potrf_inv carry, for both stages).
                # The last step has no TRSM wave — plain factor.
                LU, Linv, Uinv = lu_inv_tile(diag)
                st["_lu_Linv"] = Linv
                st["_lu_Uinv"] = Uinv
            else:
                LU = getrf_nopiv_tile(diag)
            st["_lu_T"] = LU
            if last:
                D = D.at[c, k * mb:].set(LU.T)
                us = st.pop("_us", None)
                if us is not None:
                    # fold the U store back into the collection store:
                    # us.T is Uᵀ in Aᵀ layout, i.e. every U tile (k, j>k)
                    # already sits at its Aᵀ-store position — one
                    # transpose+select instead of NT strided DUS
                    bi = jnp.arange(D.shape[0]) // nb
                    bj = jnp.arange(D.shape[1]) // mb
                    D = jnp.where(bi[:, None] > bj[None, :], us.T, D)
                st[geom.name] = D
            else:
                if colk is not None:
                    st["_lu_col_rest"] = colk[:, nb:]
            return st

        return do_getrf

    if names in (["TRSM_L"], ["TRSM_L", "TRSM_U"]):
        tl = next(g for g in wave if g.tc.name == "TRSM_L")
        ks = {t[1] for t in tl.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        if sorted(tl.tasks) != [(m, k) for m in range(k + 1, MT)]:
            return None
        tu = next((g for g in wave if g.tc.name == "TRSM_U"), None)
        if tu is not None and sorted(tu.tasks) != \
                [(k, n) for n in range(k + 1, NT)]:
            return None

        def do_trsm(st, k=k):
            D = st[geom.name]
            c = slice(k * nb, (k + 1) * nb)
            LU = st.pop("_lu_T", None)
            if LU is None:
                LU = D[c, k * mb:(k + 1) * mb].T
            col = st.pop("_lu_col_rest", None)
            if col is None:       # k == 0: no update wave preceded
                col = D[c, (k + 1) * mb:]
            rowA = st.pop("_lu_rowA", None)       # A-layout (nb, T)
            if rowA is None:
                rowA = D[(k + 1) * nb:, k * mb:(k + 1) * mb].T
            if inv_mode:
                # MAGMA-style: both panel solves are MXU matmuls
                # against the inverses the GETRF wave stashed (derived
                # inside the factorization recursion — no standalone
                # tri_inv_tile passes)
                Linv = st.pop("_lu_Linv", None)
                Uinv = st.pop("_lu_Uinv", None)
                if Linv is None or Uinv is None:
                    # robustness: recompute from the packed factor
                    L, U = lu_split(LU)
                    Linv = tri_inv_tile(L) if Linv is None else Linv
                    Uinv = tri_inv_tile(U.T).T if Uinv is None else Uinv
                solved_col = mm(Uinv.T, col)       # (U^-T)·colᵀ
                solved_rowA = mm(Linv, rowA)       # L^-1·A[k, j>k]
            else:
                L, U = lu_split(LU)
                solved_col = jax.lax.linalg.triangular_solve(
                    U, col, left_side=True, lower=False,
                    transpose_a=True)
                solved_rowA = jax.lax.linalg.triangular_solve(
                    L, rowA, left_side=True, lower=True,
                    unit_diagonal=True)
            # panel writes, ONE DUS chain per store: L/diag row panel
            # into the Aᵀ collection store, U row panel into the
            # A-layout U carry (two chains on one array would cost a
            # full store copy per step — see the fuser docstring).
            # solved_rowA is ALREADY A-layout — no transpose at write.
            # concat-then-one-DUS beats two adjacent DUS's here
            # (measured 56.9 vs 54.7 TF/s at N=32768: the second DUS
            # breaks XLA's in-place chain)
            D = D.at[c, k * mb:].set(
                jnp.concatenate([LU.T, solved_col.astype(D.dtype)],
                                axis=1))
            us = st.get("_us")
            if us is None:
                us = jnp.zeros_like(D)
            st["_us"] = us.at[k * nb:(k + 1) * nb, (k + 1) * mb:].set(
                solved_rowA.astype(D.dtype))
            st[geom.name] = D
            return st

        return do_trsm

    return None


def getrf_flops(n: int) -> float:
    """Useful FLOPs of an n×n LU (LAPACK count)."""
    return 2.0 * n ** 3 / 3.0 - n ** 2 / 2.0 + 5.0 * n / 6.0
