"""parsec_tpu — a TPU-native task-dataflow runtime with the capabilities of
PaRSEC (reference: /root/reference, ICL/UTK PaRSEC).

PaRSEC executes DAGs of micro-tasks with labeled data-flow dependencies over
distributed tiled data collections (reference: parsec/runtime.h:170-323,
parsec/parsec.c). This package re-designs that capability set TPU-first:

- The *runtime core* (taskpools, task classes, dependency tracking,
  schedulers, termination detection) mirrors the reference's contracts
  (parsec_internal.h:119-516) but is host-side Python + a native C++ core.
- The *device layer* replaces the CUDA stream pipeline
  (mca/device/cuda/device_cuda_module.c) with XLA execution: ready tasks of
  the same task class are batched and run as one vmapped XLA call so the MXU
  sees large, static-shaped matmuls instead of per-task kernel launches.
- The *distributed layer* replaces MPI remote_deps (parsec/remote_dep.c)
  with SPMD compiled execution over a jax.sharding.Mesh: owner-computes
  placement on block-cyclic collections, with XLA collectives riding ICI.

Public API (mirrors parsec_init / parsec_context_* from runtime.h):

    import parsec_tpu as parsec
    ctx = parsec.init(nb_cores=4)
    tp  = parsec.dtd.Taskpool(ctx)   # or a PTG taskpool
    ...
    ctx.add_taskpool(tp); ctx.start(); ctx.wait()
    parsec.fini(ctx)
"""

from .version import __version__
from .utils import mca_param
from .utils.debug import debug_verbose, set_verbosity
from .core.context import Context, init, fini
from .core.taskpool import Taskpool, TaskClass, Flow, FlowAccess, Task
from .core.compound import compose
from .core.future import Future, DataCopyFuture
from .core.reshape import ReshapeSpec
from . import dsl
from .dsl import dtd, ptg
from . import data
from . import device
from . import sched
from . import termdet
from . import compiled
from . import comm
from . import profiling
from . import ops
from . import analysis

__all__ = [
    "__version__",
    "init", "fini", "Context",
    "Taskpool", "TaskClass", "Flow", "FlowAccess", "Task", "compose",
    "Future", "DataCopyFuture", "ReshapeSpec",
    "dsl", "dtd", "ptg", "data", "device", "sched", "termdet",
    "compiled", "comm", "profiling", "ops", "analysis", "mca_param",
    "debug_verbose", "set_verbosity",
]
