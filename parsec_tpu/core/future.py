"""Futures and datacopy futures.

Reference: parsec/class/parsec_future.c (base future: set-once value with
blocking get and completion callbacks) and
parsec/class/parsec_datacopy_future.c (futures over data copies whose
fulfillment runs a *trigger* constructing the requested copy lazily —
the mechanism behind reshape promises, remote_dep.h:100-108).

TPU-first divergence: a "copy in another datatype/layout" is a functional
transform of an immutable array value (dtype cast, transpose, retiling),
usually jax-jittable — so a datacopy future caches one converted value per
requested :class:`~parsec_tpu.core.reshape.ReshapeSpec` and shares it
across all consumers instead of tracking per-device copy objects.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional


class Future:
    """Set-once future (parsec_future.c analog).

    ``set`` fulfills the future exactly once; ``get`` blocks; callbacks
    registered with ``on_ready`` fire on the setting thread (or
    immediately if already fulfilled).
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._ready = False
        self._value: Any = None
        self._cbs: List[Callable[[Any], None]] = []

    def is_ready(self) -> bool:
        with self._cond:
            return self._ready

    def set(self, value: Any) -> None:
        with self._cond:
            if self._ready:
                raise RuntimeError("future already fulfilled")
            self._value = value
            self._ready = True
            cbs, self._cbs = self._cbs, []
            self._cond.notify_all()
        for cb in cbs:
            cb(value)

    def get(self, timeout: Optional[float] = None) -> Any:
        with self._cond:
            if not self._cond.wait_for(lambda: self._ready, timeout):
                raise TimeoutError("future not fulfilled in time")
            return self._value

    def on_ready(self, cb: Callable[[Any], None]) -> None:
        with self._cond:
            if not self._ready:
                self._cbs.append(cb)
                return
            value = self._value
        cb(value)


class DataCopyFuture(Future):
    """Future over a data value with lazily-triggered converted copies
    (parsec_datacopy_future.c analog).

    ``get_copy(spec)`` returns the base value for ``spec=None``, else the
    value transformed by ``spec`` — computed by the *trigger* on first
    request (on the requesting thread, like the reference's reshape
    triggers running on compute or comm threads) and cached so every
    consumer of the same spec shares one conversion.
    """

    def __init__(self, value: Any = None, *,
                 trigger: Optional[Callable[[Any, Any], Any]] = None) -> None:
        super().__init__()
        if value is not None:
            self.set(value)
        # trigger(base_value, spec) -> converted value; default applies the
        # spec itself (ReshapeSpec.apply or any callable)
        self._trigger = trigger
        self._copies: Dict[Any, Any] = {}
        self._copies_lock = threading.Lock()

    def _convert(self, base: Any, spec: Any) -> Any:
        if self._trigger is not None:
            return self._trigger(base, spec)
        apply = getattr(spec, "apply", None)
        if apply is not None:
            return apply(base)
        return spec(base)

    def get_copy(self, spec: Any = None,
                 timeout: Optional[float] = None) -> Any:
        base = self.get(timeout)
        if spec is None:
            return base
        key = getattr(spec, "key", spec)
        with self._copies_lock:
            if key in self._copies:
                return self._copies[key]
        converted = self._convert(base, spec)
        with self._copies_lock:
            # a racing consumer may have converted first; keep one copy
            return self._copies.setdefault(key, converted)

    @property
    def nb_copies(self) -> int:
        with self._copies_lock:
            return len(self._copies)
