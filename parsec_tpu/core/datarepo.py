"""Data repositories.

Reference: parsec/datarepo.{c,h} (343 LoC). A repo is a hash table of
entries holding a completed task's output data, keyed by the producer task
key. The usage-limit + retain protocol (design comment datarepo.h:26-75)
lets producers and consumers race safely: the producer sets the usage limit
to the number of consumers; each consumer take decrements it; the entry is
freed when both sides are done.

In this runtime the common path attaches produced values directly to the
pending successor (taskpool.activate_dep), so repos serve (a) multi-consumer
data retention with deterministic reclamation and (b) lookups by task key
(e.g. reshape, DTD flush, profiling).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


class DataRepoEntry:
    __slots__ = ("key", "data", "usage_limit", "usage_count", "retained", "repo")

    def __init__(self, repo: "DataRepo", key, nb_flows: int):
        self.repo = repo
        self.key = key
        self.data: list = [None] * nb_flows
        self.usage_limit = 0        # set by producer: number of consumes
        self.usage_count = 0        # consumes so far
        self.retained = 1           # producer's retain; released on set_usage

    def get(self, flow_index: int) -> Any:
        obs = DataRepo.observer
        if obs is not None:
            obs("get", self.repo, self.key, flow_index)
        return self.data[flow_index]

    def set(self, flow_index: int, value: Any) -> None:
        obs = DataRepo.observer
        if obs is not None:
            obs("set", self.repo, self.key, flow_index)
        self.data[flow_index] = value


class DataRepo:
    """Hash table of :class:`DataRepoEntry` (datarepo.c analog)."""

    #: process-wide access observer ``fn(op, repo, key, flow_index)`` —
    #: installed by the dfsan race sanitizer (analysis/dfsan.py) so repo
    #: entry fills/takes on the release path are stamped too; None keeps
    #: the accessors at one attribute read of overhead
    observer = None

    def __init__(self, nb_flows: int = 1):
        self.nb_flows = nb_flows
        self._entries: Dict[Any, DataRepoEntry] = {}
        self._lock = threading.Lock()

    def lookup_or_create(self, key) -> DataRepoEntry:
        """data_repo_lookup_entry_and_create analog: returns a retained
        entry for the producer to fill."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                ent = DataRepoEntry(self, key, self.nb_flows)
                self._entries[key] = ent
            else:
                ent.retained += 1
            return ent

    def lookup(self, key) -> Optional[DataRepoEntry]:
        with self._lock:
            return self._entries.get(key)

    def entry_addto_usage_limit(self, key, delta: int) -> None:
        """data_repo_entry_addto_usage_limit analog: the producer declares
        how many consumers will take from this entry; also drops the
        producer's retain."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return
            ent.usage_limit += delta
            ent.retained -= 1
            self._maybe_free_locked(ent)

    def entry_used_once(self, key) -> None:
        """data_repo_entry_used_once analog: one consumer is done."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return
            ent.usage_count += 1
            self._maybe_free_locked(ent)

    def _maybe_free_locked(self, ent: DataRepoEntry) -> None:
        if ent.retained <= 0 and ent.usage_count >= ent.usage_limit:
            self._entries.pop(ent.key, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
