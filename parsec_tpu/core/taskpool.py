"""Taskpool and task-class structures with dependency tracking.

Mirrors:
- ``parsec_taskpool_t`` (parsec_internal.h:119-161): a DAG instance with a
  task counter, termination-detection monitor, task-class array and
  per-class data repos; registered/looked up by id (parsec.c:2069-2171).
- ``parsec_task_class_t`` (parsec_internal.h:381-425): static description of
  a task type — params, flows, incarnations, and the vtable
  (iterate_successors, release_deps, make_key, ...).
- Dependency tracking (parsec.c:1503-1649): two strategies — a *counter*
  per waiting task, or a *mask* of input-dependency bits; both keyed by the
  task key in a hash table (``parsec_hash_find_deps``).

The release-deps path (parsec.c:1694-1921) is generalized here: a completed
task's class enumerates :class:`SuccessorRef`s; the taskpool counts down /
ORs in each satisfied dependency and constructs the successor task when its
goal is reached, attaching the flowing data values.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from .task import Chore, DeviceType, Flow, FlowAccess, Task
from ..utils.debug import debug_verbose

# Dependency-tracking strategies (reference jdf.h:88-91 dep-management modes)
DEPS_COUNTER = "counter"    # parsec_update_deps_with_counter (parsec.c:1554)
DEPS_MASK = "mask"          # parsec_update_deps_with_mask (parsec.c:1601)

from ..utils import mca_param as _mca_param
_mca_param.register(
    "runtime.native_deps", True,
    help="use the C++ dependency table when the native core is available")


@dataclass
class SuccessorRef:
    """One satisfied dependency flowing from a completed task to a successor.

    Produced by ``TaskClass.iterate_successors`` (the generated
    iterate_successors of jdf2c.c); consumed by ``Taskpool.activate_dep``.
    """
    task_class: "TaskClass"          # successor's class
    locals: Tuple[int, ...]          # successor's parameter assignment
    flow_name: str                   # successor's input flow receiving data
    value: Any = None                # payload (None for CTL deps)
    dep_index: int = 0               # input-dep bit for mask mode
    priority: int = 0
    src_flow: Optional[str] = None   # producer's flow (planners/native exec)
    reshape_spec: Any = None         # composed reshape (core/reshape.py);
                                     # resolved before the value fans out


class CancelledError(RuntimeError):
    """A taskpool was cancelled (deadline expiry or explicit
    Submission.cancel) — distinct from a body failure so serving-side
    waiters can tell 'your deadline passed' from 'your code crashed'."""


@dataclass
class DataRef:
    """A terminal output dependency: write a value back to a collection
    (the ``-> A(k, k)`` form of a JDF dep)."""
    collection: Any                  # data.collection.DataCollection
    key: Tuple[int, ...]
    value: Any = None


class TaskClass:
    """Static description of a task type (parsec_task_class_t analog).

    DSLs (PTG/DTD) construct instances and fill the vtable callables:

    - ``iterate_successors(task) -> Iterable[SuccessorRef | DataRef]``
    - ``deps_goal(locals) -> int`` — number of input deps (counter mode) or
      bitmask of input-dep indices (mask mode) that must be satisfied
    - ``make_key(locals)``, ``priority(locals)``
    """

    def __init__(self, name: str, tc_id: int, params: Sequence[str],
                 flows: Sequence[Flow], deps_mode: str = DEPS_COUNTER):
        self.name = name
        self.tc_id = tc_id
        self.params = tuple(params)
        self.flows: List[Flow] = []
        for i, f in enumerate(flows):
            f.index = i
            self.flows.append(f)
        self.flow_by_name: Dict[str, Flow] = {f.name: f for f in self.flows}
        self.deps_mode = deps_mode
        self.incarnations: List[Chore] = []
        self.properties: Dict[str, Any] = {}
        # vtable — filled by the DSL layer
        self.iterate_successors: Callable[[Task], Iterable] = lambda task: ()
        self.deps_goal: Callable[[Tuple[int, ...]], int] = lambda locals: 0
        self.priority_fn: Callable[[Tuple[int, ...]], int] = lambda locals: 0
        self.time_estimate: Optional[Callable[[Task], float]] = None
        self.on_complete: Optional[Callable[[Task], None]] = None

    # -- vtable defaults ---------------------------------------------------
    def make_key(self, locals: Tuple[int, ...]) -> Tuple[int, Tuple[int, ...]]:
        return (self.tc_id, tuple(locals))

    def add_chore(self, chore: Chore) -> "TaskClass":
        self.incarnations.append(chore)
        return self

    def chore_for(self, device_type: DeviceType) -> Optional[Chore]:
        for c in self.incarnations:
            if c.device_type & device_type:
                return c
        return None

    @property
    def output_flows(self) -> List[Flow]:
        return [f for f in self.flows
                if (f.access & FlowAccess.WRITE) and not f.is_ctl]

    @property
    def input_flows(self) -> List[Flow]:
        return [f for f in self.flows
                if (f.access & FlowAccess.READ) and not f.is_ctl]

    def __repr__(self) -> str:
        return f"<TaskClass {self.name} id={self.tc_id}>"


class _PendingDeps:
    """Hash-table dependency tracking for not-yet-ready tasks.

    Entry per task key: satisfied counter/mask + accumulated input values.
    Reference: parsec_hash_find_deps (parsec.c:1525) + update functions.
    Striped locks stand in for the reference's bucket-locked hash table
    (class/parsec_hash_table.c).

    When the native core is available (parsec_tpu/_native), the
    counter/mask accounting runs in the C++ dependency table (pdep_*) on
    64-bit task keys — the same key model the reference uses
    (parsec_key_t) — while input values stay Python-side under the stripe
    locks. Each provider writes its value *before* counting, so whichever
    provider completes the goal observes every value (mutex ordering).
    """

    _NSTRIPES = 64

    def __init__(self) -> None:
        self._entries: Dict[Any, Dict[str, Any]] = {}
        self._locks = [threading.Lock() for _ in range(self._NSTRIPES)]
        # dfsan race sanitizer (analysis/dfsan.py): when installed, the
        # stripe locks report acquisition order so lock-order inversions
        # are flagged; None keeps the hot path a bare Lock
        self.sanitizer = None
        self._native = None
        self._native_lib = None
        from ..utils import mca_param
        if mca_param.get("runtime.native_deps", True):
            from .. import _native
            lib = _native.load()
            if lib is not None:
                self._native_lib = lib
                self._native = lib.pdep_new()

    def __del__(self):
        if getattr(self, "_native", None):
            self._native_lib.pdep_free(self._native)
            self._native = None

    def _lock_for(self, key) -> threading.Lock:
        return self._stripe_lock(hash(key) % self._NSTRIPES)

    def _stripe_lock(self, stripe: int):
        lock = self._locks[stripe]
        san = self.sanitizer
        if san is not None:
            return san.wrap_lock(lock, "pdep", stripe)
        return lock

    @staticmethod
    def _key64(key) -> int:
        return hash(key) & 0xFFFFFFFFFFFFFFFF

    def _pop_data(self, key, priority: int) -> Dict[str, Any]:
        with self._lock_for(key):
            ent = self._entries.pop(key, None)
        if ent is None:
            ent = {"data": {}, "priority": priority}
        ent["priority"] = max(ent["priority"], priority)
        return ent

    @staticmethod
    def _count_locked(ent: Dict[str, Any], key, flow_name: str, value: Any,
                      dep_index: int, goal: int, mode: str,
                      priority: int) -> bool:
        """Apply ONE satisfied dep to an entry; True when the goal is
        reached. Caller holds the entry's stripe lock. The single copy of
        the count/mask accounting shared by :meth:`update` and
        :meth:`update_batch` — the two must never diverge."""
        if value is not None:
            ent["data"][flow_name] = value
        ent["priority"] = max(ent["priority"], priority)
        if mode == DEPS_MASK:
            bit = 1 << dep_index
            if ent["mask"] & bit:
                raise RuntimeError(
                    f"dependency bit {dep_index} satisfied twice for {key}")
            ent["mask"] |= bit
            return ent["mask"] == goal
        ent["count"] += 1
        return ent["count"] == goal

    def update(self, key, flow_name: str, value: Any, dep_index: int,
               goal: int, mode: str, priority: int) -> Optional[Dict[str, Any]]:
        """Record one satisfied dep; return the entry if the goal is reached
        (caller then constructs and schedules the task)."""
        if self._native is not None:
            import ctypes
            if value is not None:
                with self._lock_for(key):
                    ent = self._entries.get(key)
                    if ent is None:
                        ent = {"data": {}, "priority": priority}
                        self._entries[key] = ent
                    ent["data"][flow_name] = value
            prio_out = ctypes.c_int32(priority)
            rc = self._native_lib.pdep_update(
                self._native, self._key64(key), goal, dep_index,
                1 if mode == DEPS_MASK else 0, priority,
                ctypes.byref(prio_out))
            if rc == -1:
                raise RuntimeError(
                    f"dependency bit {dep_index} satisfied twice for {key}")
            if rc == 1:
                return self._pop_data(key, prio_out.value)
            return None
        with self._lock_for(key):
            ent = self._entries.get(key)
            if ent is None:
                ent = {"count": 0, "mask": 0, "data": {}, "priority": priority}
                self._entries[key] = ent
            if self._count_locked(ent, key, flow_name, value, dep_index,
                                  goal, mode, priority):
                del self._entries[key]
                return ent
            return None

    def update_batch(self, items) -> List[Tuple[int, Dict[str, Any]]]:
        """Batched :meth:`update`: ``items`` is a sequence of
        ``(key, flow_name, value, dep_index, goal, mode, priority)``
        tuples. Entries are grouped by lock stripe so each stripe lock is
        taken ONCE per batch instead of once per dependency — the
        release-deps hot loop's dominant lock traffic when a completed
        task fans out to many successors. Returns ``(item_index, entry)``
        for every dependency that completed its target's goal."""
        if self._native is not None:
            # the native table does its own per-key synchronization, so
            # there is no stripe-lock traffic to coalesce — delegate per
            # item to the scalar path
            out = []
            for i, (key, flow_name, value, dep_index, goal, mode,
                    priority) in enumerate(items):
                ent = self.update(key, flow_name, value, dep_index, goal,
                                  mode, priority)
                if ent is not None:
                    out.append((i, ent))
            return out
        by_stripe: Dict[int, List[int]] = {}
        for i, item in enumerate(items):
            by_stripe.setdefault(hash(item[0]) % self._NSTRIPES,
                                 []).append(i)
        out = []
        for stripe, idxs in by_stripe.items():
            with self._stripe_lock(stripe):
                for i in idxs:
                    (key, flow_name, value, dep_index, goal, mode,
                     priority) = items[i]
                    ent = self._entries.get(key)
                    if ent is None:
                        ent = {"count": 0, "mask": 0, "data": {},
                               "priority": priority}
                        self._entries[key] = ent
                    if self._count_locked(ent, key, flow_name, value,
                                          dep_index, goal, mode, priority):
                        del self._entries[key]
                        out.append((i, ent))
        return out

    def finalize(self, key, goal: int, mode: str) -> Optional[Dict[str, Any]]:
        """For DSLs whose goal is only known after linking (DTD): check
        whether the already-accumulated count/mask meets the final goal;
        if so pop and return the entry."""
        if self._native is not None:
            import ctypes
            prio_out = ctypes.c_int32(0)
            rc = self._native_lib.pdep_finalize(
                self._native, self._key64(key), goal,
                1 if mode == DEPS_MASK else 0, ctypes.byref(prio_out))
            if rc == 1:
                return self._pop_data(key, prio_out.value)
            return None
        with self._lock_for(key):
            ent = self._entries.get(key)
            if ent is None:
                return None
            done = (ent["mask"] == goal) if mode == DEPS_MASK \
                else (ent["count"] == goal)
            if done:
                del self._entries[key]
                return ent
            return None

    def __len__(self) -> int:
        if self._native is not None:
            return int(self._native_lib.pdep_size(self._native))
        return len(self._entries)


_tp_counter = itertools.count(1)


class Taskpool:
    """A DAG instance (parsec_taskpool_t analog).

    Lifecycle: construct → ``context.add_taskpool`` (installs termdet,
    runs ``startup_hook`` to seed no-predecessor tasks) → tasks flow through
    the scheduler → termdet fires ``_on_terminated`` when
    ``nb_tasks == nb_pending_actions == 0``.
    """

    def __init__(self, name: str = "taskpool"):
        self.name = name
        self.taskpool_id = next(_tp_counter)
        self.task_classes: List[TaskClass] = []
        self._tc_by_name: Dict[str, TaskClass] = {}
        self.context = None                      # set by add_taskpool
        self.pending = _PendingDeps()
        self.monitor = None                      # termdet monitor
        self.on_enqueue: Optional[Callable] = None
        self.on_complete: Optional[Callable] = None
        self.error: Optional[BaseException] = None
        self._complete_evt = threading.Event()
        self.priority = 0
        # cancellation (serving deadlines, Context.submit): when set,
        # queued-but-not-running tasks are DROPPED at select time
        # (scheduler/worker loop) instead of executed; in-flight tasks
        # drain through the normal completion path. Set via cancel().
        self.cancelled = False
        # multi-tenant serving metadata. fair_weight drives the wfq
        # scheduler's stride (sched/fair.py); tenant_name attributes
        # per-tenant PINS accounting; rank_scope restricts which peer
        # deaths can fail this pool (comm engines abort only pools
        # whose scope contains the dead rank — None = every rank, the
        # pre-serving fail-stop behavior).
        self.fair_weight: float = 1.0
        self.tenant_name: Optional[str] = None
        self.rank_scope: Optional[frozenset] = None
        # True when a supervisor (the serving runtime) owns this pool's
        # error reporting: a failure then never lands in the context's
        # aborted list, so other callers' Context.wait stays clean
        self.error_owned = False
        # request-scoped tracing (profiling/spans.py): serving
        # submissions set trace_rid (deterministic from the pool name,
        # identical on every rank) and root_span (the submission root
        # every startup task / admission park parents to). None keeps
        # the span path COMPLETELY off — plain attribute reads are the
        # only hot-path cost.
        self.trace_rid: Optional[str] = None
        self.root_span: Optional[str] = None
        # lineage record: (class name, locals) of every locally-completed
        # task (runtime.lineage) — after a peer death the survivors'
        # union of these is the completed-set input of
        # data.recovery.plan_recovery. GIL-atomic set.add on the release
        # path; measured noise vs the 14.2k tasks/s baseline.
        self.completed_tasks: set = set()
        # DSL hook: enumerate startup (no-predecessor) tasks
        self.startup_hook: Callable[["Taskpool"], List[Task]] = lambda tp: []

    # -- task classes -----------------------------------------------------
    def add_task_class(self, tc: TaskClass) -> TaskClass:
        self.task_classes.append(tc)
        self._tc_by_name[tc.name] = tc
        return tc

    def get_task_class(self, name: str) -> TaskClass:
        """Lookup by name (PTG taskpools shadow ``task_class`` with the
        class-builder, so the lookup has its own name)."""
        return self._tc_by_name[name]

    # -- static hazard lint (analysis/lint.py) ----------------------------
    def validate(self, mode: str = "error", max_tasks: int = 0):
        """Run the static dataflow lint over this taskpool and return
        the :class:`~parsec_tpu.analysis.lint.LintReport`.

        ``mode="error"`` raises :class:`~parsec_tpu.analysis.lint.
        HazardError` when any error-severity finding (undeclared
        producer, WAW/WAR hazard, access-mode violation, dependency
        cycle, phantom target) is present; ``mode="warn"`` logs the
        findings instead.  Task classes without closed-form PTG specs
        (DTD) are skipped — the dfsan runtime sanitizer covers those.
        Also invoked at registration when the ``analysis.lint`` MCA
        param is ``warn``/``error`` (Context.add_taskpool).
        """
        from ..analysis.lint import validate as _validate
        return _validate(self, mode=mode, max_tasks=max_tasks)

    def new_task_class(self, name: str, params: Sequence[str],
                       flows: Sequence[Flow],
                       deps_mode: str = DEPS_COUNTER) -> TaskClass:
        tc = TaskClass(name, len(self.task_classes), params, flows, deps_mode)
        return self.add_task_class(tc)

    # -- termdet glue (reference parsec_internal.h:123-145) ---------------
    def set_nb_tasks(self, n: int) -> None:
        self.monitor.set_nb_tasks(n)

    def addto_nb_tasks(self, d: int) -> None:
        self.monitor.addto_nb_tasks(d)

    def addto_runtime_actions(self, d: int) -> None:
        self.monitor.addto_runtime_actions(d)

    @property
    def nb_tasks(self) -> int:
        return self.monitor.nb_tasks if self.monitor else 0

    def _on_terminated(self) -> None:
        if self._complete_evt.is_set():
            # terminated is final: an abort()ed pool's still-queued
            # tasks keep draining, and the monitor re-fires when their
            # counters hit zero — a refire must not re-report the pool
            # to the context (it would poison a LATER wait, e.g. the
            # recovery replay's, with the stale abort)
            return
        debug_verbose(4, "taskpool", "%s terminated", self.name)
        self._complete_evt.set()
        if self.on_complete is not None:
            self.on_complete(self)
        if self.context is not None:
            self.context._taskpool_terminated(self)

    def abort(self, exc: BaseException) -> None:
        """parsec_abort analog: a task body failed — record the error and
        force-terminate so waiters are released instead of hanging."""
        if self.error is None:
            self.error = exc
        self._on_terminated()

    def cancel(self, exc: Optional[BaseException] = None) -> None:
        """Cancel this taskpool (serving deadlines / Context.submit):
        not-yet-running tasks are dropped at select time (the
        ``cancelled`` flag — schedulers and the worker loop decrement
        ``nb_tasks`` instead of executing), in-flight tasks drain
        through the normal completion path, and waiters are released
        now via the abort machinery. Termination is idempotent (PR 6),
        so draining tasks re-firing termdet cannot poison a later wait
        on a DIFFERENT pool — cancellation is a per-taskpool failure
        unit."""
        self.cancelled = True
        self.abort(exc if exc is not None
                   else CancelledError(f"taskpool {self.name} cancelled"))

    @property
    def completed(self) -> bool:
        return self._complete_evt.is_set()

    def wait_completed(self, timeout: Optional[float] = None) -> bool:
        ok = self._complete_evt.wait(timeout)
        if self.error is not None:
            raise RuntimeError(
                f"taskpool {self.name} aborted: {self.error}") from self.error
        return ok

    # -- dependency activation (parsec.c:1694-1780 analog) ----------------
    def _ready_task(self, ref: SuccessorRef, ent: Dict[str, Any]) -> Task:
        """Construct the ready Task for a goal-completing entry — the one
        copy shared by the scalar and batched activation paths."""
        tc = ref.task_class
        task = Task(self, tc, ref.locals,
                    priority=max(ent["priority"], tc.priority_fn(ref.locals)))
        task.data.update(ent["data"])
        return task

    def activate_dep(self, ref: SuccessorRef) -> Optional[Task]:
        """Count one satisfied input dep of ``ref``'s target task; if that
        completes the target's goal, construct the ready Task and return it
        (caller schedules it)."""
        tc = ref.task_class
        ent = self.pending.update(tc.make_key(ref.locals), ref.flow_name,
                                  ref.value, ref.dep_index,
                                  tc.deps_goal(ref.locals), tc.deps_mode,
                                  ref.priority)
        if ent is None:
            return None
        return self._ready_task(ref, ent)

    def activate_deps(self, refs: Sequence[SuccessorRef]) -> List[Task]:
        """Batched :meth:`activate_dep`: count all of a completed task's
        satisfied deps in one striped-lock pass (``runtime.release_batch``)
        and return every successor whose goal was reached. Semantics are
        identical to calling ``activate_dep`` per ref; only the lock
        traffic changes."""
        if len(refs) == 1:
            task = self.activate_dep(refs[0])
            return [task] if task is not None else []
        items = []
        for ref in refs:
            tc = ref.task_class
            items.append((tc.make_key(ref.locals), ref.flow_name, ref.value,
                          ref.dep_index, tc.deps_goal(ref.locals),
                          tc.deps_mode, ref.priority))
        return [self._ready_task(refs[i], ent)
                for i, ent in self.pending.update_batch(items)]

    def __repr__(self) -> str:
        return f"<Taskpool {self.name} id={self.taskpool_id}>"
