"""Native static-DAG executor.

Runs a PTG taskpool through the C++ engine (``parsec_tpu/_native``):
dependency countdown, priority work-stealing queues and worker threads
live in C++ (the role parsec/scheduling.c + mca/sched play in the
reference, which are native C); Python is entered only to run task
bodies. Bodies that call numpy/JAX release the GIL during their heavy
work, so the C++ workers genuinely overlap.

Value passing: each edge carries the producer flow's output to the
consumer flow (the release-deps data attachment, parsec.c:1694-1780);
collection-sourced inputs resolve through the class's data_lookup.
Producer outputs are refcounted per consumer and dropped as soon as the
last consumer ran — the countdown is an ATOMIC in the native core
(``pgraph_consume``; the engine owns ``nconsumers``), so concurrent
bodies never serialize on a Python refcount lock.

Use when the DAG is statically enumerable (always true for PTG). The
dynamic paths (DTD insertion, multi-rank) use the host runtime; the
compiled wavefront path replaces both when the whole DAG can become one
XLA program.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Tuple

import numpy as np

from .task import DeviceType, Task
from .taskpool import DataRef
from .. import _native


class NativeDAGExecutor:
    """Execute a PTG taskpool on the C++ engine."""

    def __init__(self, tp, nworkers: int = 4,
                 device_type: DeviceType = DeviceType.CPU, hbm=None):
        """``hbm``: optional :class:`~..device.hbm.HBMManager` — tile
        write-backs are then budget-tracked exactly like the host
        runtime's completion path (pinned put → write → unpin)."""
        lib = _native.load()
        if lib is None:
            raise RuntimeError("native core unavailable (no g++?)")
        self.lib = lib
        self.tp = tp
        self.nworkers = max(1, nworkers)
        self.device_type = device_type
        self.hbm = hbm

        # ---- enumerate the task space
        self.tasks: List[Tuple[object, Tuple[int, ...]]] = []
        tid: Dict[Tuple[str, Tuple], int] = {}
        for tc in tp.task_classes:
            for p in tc.enumerate_space():
                tid[(tc.name, p)] = len(self.tasks)
                self.tasks.append((tc, p))
        n = len(self.tasks)

        # ---- dry-run successor iterators to build the edge list
        # edge: (src_tid, src_flow, dst_flow, composed reshape spec) —
        # dep [type=...] conversions are static per edge, applied when
        # the consumer's input is attached (parsec_local_reshape analog)
        self.in_edges: List[List[Tuple[int, str, str, object]]] = \
            [[] for _ in range(n)]
        esrc, edst = [], []
        self.nconsumers = np.zeros(n, dtype=np.int64)
        for i, (tc, p) in enumerate(self.tasks):
            dry = Task(tp, tc, p)
            for f in tc.flows:
                dry.data[f.name] = 0
                dry.output[f.name] = 0
            for ref in tc.iterate_successors(dry):
                if isinstance(ref, DataRef):
                    continue
                j = tid[(ref.task_class.name, tuple(ref.locals))]
                esrc.append(i)
                edst.append(j)
                self.in_edges[j].append(
                    (i, ref.src_flow, ref.flow_name, ref.reshape_spec))
                self.nconsumers[i] += 1

        ndeps = np.array([len(e) for e in self.in_edges], dtype=np.int32)
        prio = np.array([tc.priority_fn(p) for tc, p in self.tasks],
                        dtype=np.int32)
        esrc = np.asarray(esrc, dtype=np.uint32)
        edst = np.asarray(edst, dtype=np.uint32)

        self._outputs: List[Optional[dict]] = [None] * n
        self._error: Optional[BaseException] = None

        self._body_cb = _native.BODY_FN(self._run_body)   # keep alive
        self._g = lib.pgraph_new(
            n, ndeps.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            prio.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(esrc),
            esrc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            edst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            self._body_cb, self.nworkers)
        if not self._g:
            raise MemoryError("pgraph_new failed")
        self.n_tasks = n

    # ------------------------------------------------------------------
    def _run_body(self, tid: int, worker: int) -> int:
        try:
            tc, p = self.tasks[tid]
            task = Task(self.tp, tc, p)
            for (i, src_flow, dst_flow, spec) in self.in_edges[tid]:
                out = self._outputs[i]
                v = None if out is None else out.get(src_flow)
                if spec is not None and v is not None:
                    v = spec.apply(v)
                task.data[dst_flow] = v
            lookup = getattr(tc, "data_lookup", None)
            if lookup is not None:
                lookup(task)
            chore = tc.chore_for(self.device_type) or \
                tc.chore_for(DeviceType.ALL) or \
                (tc.incarnations[0] if tc.incarnations else None)
            if chore is None:
                raise RuntimeError(f"no body for {tc.name}")
            result = chore.hook(task, *task.input_values())
            # THE shared body-result contract (core.task.normalize_
            # outputs): the old inline zip silently truncated on arity
            # mismatch where the host runtime raises — engine choice
            # must not change what a return value means
            from .task import normalize_outputs
            task.output.update(normalize_outputs(
                result, [f.name for f in tc.output_flows], task))
            # terminal collection write-backs; successor activation is
            # native (the engine counts down deps from the edge list).
            # Budget-tracked when an HBM manager is attached — the same
            # pinned track → write → unpin protocol as the host
            # runtime's complete_task.
            from ..device.hbm import track_collection_write
            for ref in tc.iterate_successors(task):
                if isinstance(ref, DataRef):
                    mkey = track_collection_write(
                        self.hbm, ref.collection, ref.key, ref.value)
                    ref.collection.write_tile(ref.key, ref.value)
                    if mkey is not None:
                        self.hbm.unpin(mkey)
            if self.nconsumers[tid]:
                self._outputs[tid] = {f.name: task.output.get(
                    f.name, task.data.get(f.name)) for f in tc.flows}
            # drop predecessor outputs once their last consumer ran:
            # the countdown is the engine's atomic (pgraph_consume) —
            # whichever consumer decrements to zero sees 1 exactly once,
            # so the Python side needs no lock around the drop
            for (i, _sf, _df, _spec) in self.in_edges[tid]:
                if self.lib.pgraph_consume(self._g, i) == 1:
                    self._outputs[i] = None
            return 0
        except BaseException as exc:  # noqa: BLE001 — crossing the C ABI
            self._error = exc
            return 1

    def run(self) -> None:
        rc = self.lib.pgraph_run(self._g)
        if rc == 1 and self._error is not None:
            raise RuntimeError(
                f"task body failed: {self._error}") from self._error
        if rc != 0:
            raise RuntimeError(f"native DAG execution failed (rc={rc})")

    def __del__(self):
        # interpreter-shutdown tolerant: at teardown the ctypes library
        # (or its function pointers) may already be torn down — leaking
        # to the OS then is correct, raising from __del__ is not
        g = getattr(self, "_g", None)
        lib = getattr(self, "lib", None)
        if g and lib is not None:
            try:
                lib.pgraph_free(g)
            except (AttributeError, TypeError, OSError):
                pass
        try:
            self._g = None
        except Exception:  # noqa: BLE001 — __del__ must never raise
            pass
