"""Reshape engine: converting data between datatypes/layouts across deps.

Reference: parsec/parsec_reshape.c (771 LoC) — when a dependency's
datatype differs from the producer's output, the runtime interposes a
*reshape promise* (a datacopy future, remote_dep.h:100-108) whose trigger
converts the data; the conversion runs on a compute or comm thread and is
shared by every consumer needing the same type
(parsec_local_reshape, remote_dep_mpi.c:642).

TPU-first design: a "datatype" is a :class:`ReshapeSpec` — a named,
composable functional transform (dtype cast, transpose, arbitrary
callable). Producer-side specs (``Out.reshape``) convert before the value
fans out; consumer-side specs (``In.reshape``) convert on receipt. Both
compose into one spec resolved through a shared
:class:`~parsec_tpu.core.future.DataCopyFuture`, so N consumers asking for
the same layout trigger exactly one conversion (the promise-sharing
property of the reference). Transforms on jax arrays trace into XLA, so a
conversion of an HBM-resident tile runs on-device with no host bounce.
"""

from __future__ import annotations

import itertools
import weakref
from typing import Any, Callable, Optional

_spec_ids = itertools.count(1)


class ReshapeSpec:
    """A named layout/datatype conversion (the parsec_datatype_t analog of
    a dep's ``[type = ...]`` annotation in JDF).

    ``dtype``: cast target (numpy dtype name or jax dtype).
    ``transpose``: swap the last two axes.
    ``fn``: arbitrary transform ``value -> value`` (applied last).
    For the compiled executors, which apply specs to whole gathered
    stacks ``(batch, mb, nb)``, ``fn`` must be batch-safe — operate on
    the last two axes only (dtype/transpose are batch-safe by
    construction). The host runtime applies specs per value.
    ``name``: the human-readable half of the spec's identity. The FULL
    conversion identity is ``(name, fn-object)`` (see :attr:`key`):
    caches and the planners cannot verify behavioral equality of two
    same-named ``fn`` specs, so two separately-built instances with the
    same name are NOT the same conversion unless they share the same
    ``fn`` object. Specs built only from dtype/transpose get a
    canonical name automatically (and ``fn is None``, so name alone
    does identify them); specs with ``fn`` get a unique name unless
    named. Same-named fn specs landing on one gathered flow must share
    the SAME spec instance (or at least the same ``fn`` object) or
    planning rejects the taskpool.
    """

    def __init__(self, dtype: Any = None, transpose: bool = False,
                 fn: Optional[Callable[[Any], Any]] = None,
                 name: Optional[str] = None):
        self.dtype = dtype
        self.transpose = transpose
        self.fn = fn
        # compose() memo: same (self, then) pair -> SAME composed spec
        # object, so (name, fn) identity holds across the per-edge
        # compose calls iterate_successors makes (a fresh lambda per
        # call would defeat conversion sharing and wave batching).
        # Weak values bound the cache (ADVICE r5 #2): an entry lives
        # exactly as long as something (a plan, an in-flight dep) holds
        # the composed spec, so a long-lived producer spec composed
        # against many transient consumer specs no longer accumulates
        # entries — and pins — forever.
        self._compose_cache: "weakref.WeakValueDictionary[int, ReshapeSpec]" \
            = weakref.WeakValueDictionary()
        if name is None:
            if fn is None:
                name = f"cast:{dtype}:T{int(transpose)}"
            else:
                name = f"fn:{next(_spec_ids)}"
        self.name = name

    @property
    def key(self):
        # (name, fn-object): name alone is the documented conversion
        # identity, but caches keyed by it (DataCopyFuture's shared
        # conversions, compiled-plan signatures) cannot verify
        # behavioral equality of two same-named fn specs — including
        # the fn object makes such a pair MISS (each edge converts
        # correctly) instead of silently sharing one edge's conversion
        return (self.name, self.fn)

    def apply(self, value: Any) -> Any:
        if value is None:
            return None
        out = value
        if self.dtype is not None:
            astype = getattr(out, "astype", None)
            if astype is not None:
                out = astype(self.dtype)
            else:
                import numpy as np
                out = np.asarray(out, dtype=self.dtype)
        if self.transpose:
            out = out.swapaxes(-1, -2)
        if self.fn is not None:
            out = self.fn(out)
        return out

    def compose(self, then: Optional["ReshapeSpec"]) -> "ReshapeSpec":
        """Sequential composition: ``self`` then ``then`` (producer-side
        reshape followed by consumer-side reshape). Memoized per
        ``then`` instance (weakly — see ``_compose_cache``): every edge
        composing the same pair while any consumer still holds the
        composed spec shares ONE spec object (one ``fn``, one cache
        key, one wave-group signature). The id() key is safe both ways:
        while an entry lives, the composed spec's closure holds
        ``then`` strongly, so its id cannot be recycled; and the entry
        dies WITH the composed spec, so a recycled id can never alias a
        stale entry."""
        if then is None:
            return self
        cached = self._compose_cache.get(id(then))
        if cached is not None:
            return cached
        spec = ReshapeSpec(fn=lambda v, a=self, b=then: b.apply(a.apply(v)),
                           name=f"{self.name}>>{then.name}")
        self._compose_cache[id(then)] = spec
        return spec

    def __call__(self, value: Any) -> Any:
        return self.apply(value)

    def __repr__(self) -> str:
        return f"<ReshapeSpec {self.name}>"


def compose_specs(producer: Optional[ReshapeSpec],
                  consumer: Optional[ReshapeSpec]) -> Optional[ReshapeSpec]:
    """Combine an Out-side and an In-side spec into the single conversion
    a dep needs (either side may be absent)."""
    if producer is None:
        return consumer
    return producer.compose(consumer)


def resolve_reshape(value: Any, spec: Optional[ReshapeSpec]) -> Any:
    """Resolve a possibly-promised, possibly-reshaped dep value: futures
    yield their (cached, shared) converted copy; concrete values convert
    directly (parsec_local_reshape analog)."""
    from .future import DataCopyFuture
    if isinstance(value, DataCopyFuture):
        return value.get_copy(spec)
    if spec is not None:
        return spec.apply(value)
    return value
