from .task import FlowAccess, Flow, Task, TaskStatus, Chore, DeviceType, HookReturn
from .taskpool import Taskpool, TaskClass
from .context import Context, init, fini
from .compound import compose
from . import datarepo
