"""Compound taskpools: sequential composition.

Reference: parsec_compose (runtime.h:518) / compound.c (134 LoC) — a
compound taskpool runs its members one after another; member N+1 is
enqueued when member N terminates.
"""

from __future__ import annotations

from typing import List

from .taskpool import Taskpool


class CompoundTaskpool(Taskpool):
    def __init__(self, members: List[Taskpool]):
        super().__init__(name="compound(" + "+".join(m.name for m in members) + ")")
        self.members = list(members)
        self._next = 0
        self.startup_hook = self._compound_startup

    def _compound_startup(self, tp) -> List:
        # one synthetic task: "run all members in sequence"
        self.set_nb_tasks(1)
        self._start_next()
        return []

    def _start_next(self) -> None:
        if self._next >= len(self.members):
            # all members done → compound done (monitor has 1 synthetic task)
            self.addto_nb_tasks(-1)
            return
        member = self.members[self._next]
        self._next += 1
        prev_cb = member.on_complete

        def _chain(tp, _prev=prev_cb):
            if _prev is not None:
                _prev(tp)
            if tp.error is not None:
                # aborted member: don't run later stages on failed data —
                # propagate the abort to the compound (parsec_abort analog)
                self.abort(tp.error)
                return
            self._start_next()

        member.on_complete = _chain
        self.context.add_taskpool(member)


def compose(a: Taskpool, b: Taskpool) -> CompoundTaskpool:
    """parsec_compose analog: run ``a`` then ``b``. Composes iteratively:
    compose(compose(a, b), c) flattens into one compound."""
    if isinstance(a, CompoundTaskpool) and a.context is None:
        a.members.append(b)
        a.name = "compound(" + "+".join(m.name for m in a.members) + ")"
        return a
    return CompoundTaskpool([a, b])
