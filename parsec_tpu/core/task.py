"""Task, flow and chore structures.

Mirrors the reference's core runtime objects:
- ``parsec_task_t`` (parsec_internal.h:503-516): runtime task instance with
  locals (parameter assignments), per-flow data, priority, chore mask and
  status (statuses at parsec_internal.h:464-469).
- ``parsec_flow_t`` (parsec_description_structures.h:92-106): named data
  access of a task class with access mode READ/WRITE/RW/CTL.
- ``__parsec_chore_t`` (parsec_internal.h:368-374): an *incarnation* of a
  task class on a device type, with an optional ``evaluate`` predicate and
  the executable ``hook``.

TPU-first divergence: bodies are **functional** — a chore takes the input
tile values and returns the output tile values for its WRITE/RW flows,
instead of mutating buffers in place. Functional bodies are what XLA can
trace, vmap-batch and fuse; the runtime owns the store-back.
"""

from __future__ import annotations

import enum
import itertools
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


class FlowAccess(enum.IntFlag):
    """Access mode of a flow (reference PARSEC_FLOW_ACCESS_* / SYM_INOUT)."""
    NONE = 0
    READ = 1
    WRITE = 2
    RW = 3
    CTL = 4      # control-only dependency, no data payload


class DeviceType(enum.IntFlag):
    """Device type bits (reference device.h:62-72)."""
    NONE = 0
    CPU = 1
    RECURSIVE = 2
    TPU = 4
    ALL = CPU | RECURSIVE | TPU


class HookReturn(enum.IntEnum):
    """Chore hook return codes (reference PARSEC_HOOK_RETURN_*)."""
    DONE = 0        # body executed, proceed to completion
    AGAIN = 1       # reschedule (priority demoted), e.g. resource busy
    ASYNC = 2       # body will complete asynchronously (device pipeline)
    NEXT = 3        # try the next incarnation
    ERROR = -1


class TaskStatus(enum.IntEnum):
    """Task lifecycle (reference parsec_internal.h:464-469)."""
    NONE = 0
    PREPARE_INPUT = 1
    EVAL = 2
    HOOK = 3
    PREPARE_OUTPUT = 4
    COMPLETE = 5


@dataclass
class Flow:
    """A named dataflow of a task class (parsec_flow_t analog)."""
    name: str
    access: FlowAccess
    index: int = -1          # assigned when attached to a task class

    @property
    def is_ctl(self) -> bool:
        return bool(self.access & FlowAccess.CTL)


@dataclass
class Chore:
    """One incarnation of a task class on a device type.

    ``hook(task, *inputs) -> outputs`` where ``inputs`` are the values of
    the task's flows in declaration order and ``outputs`` the new values of
    its WRITE/RW flows in declaration order (a single value may be returned
    for a single output flow). ``evaluate`` may veto this incarnation for a
    particular task (reference __parsec_chore_t.evaluate).
    """
    device_type: DeviceType
    hook: Callable[..., Any]
    evaluate: Optional[Callable[["Task"], bool]] = None
    # device-layer hints (reference gpu properties, jdf2c.c:6561-6590)
    weight: Optional[Callable[["Task"], float]] = None
    batchable: bool = True   # TPU: may be vmap-batched with same-class tasks
    # Optional hand-written batched form used by the compiled executor in
    # place of vmap(hook): ``batch_hook(*stacked_tiles) -> stacked outs``.
    # For ops whose batched lowering is poor on TPU (triangular solves),
    # a class-specific reformulation (e.g. one wide-RHS solve) is far
    # faster than the mechanical vmap. ``batch_hook_shared`` names input
    # flows the hook assumes hold ONE tile across the whole batch; the
    # executor verifies this per group and falls back to vmap otherwise.
    batch_hook: Optional[Callable[..., Any]] = None
    batch_hook_shared: Optional[Sequence[str]] = None
    # Hooks that are NOT batchable as-is (they read per-task metadata,
    # e.g. DTD's woven argspec) can still opt into manager batching by
    # providing BOTH of: ``batch_sig(task) -> hashable`` — an extra
    # grouping key such that tasks with equal keys share one pure body —
    # and ``batch_body(task) -> fn(*flow_values)`` — that pure body
    # (UNJITTED; the device jits the vmapped wrapper). Used by
    # dtd.insert_task(pure=True) so same-shape DTD tiles batch like
    # PTG tasks do.
    batch_sig: Optional[Callable[["Task"], Any]] = None
    batch_body: Optional[Callable[["Task"], Callable[..., Any]]] = None


_task_counter = itertools.count()


class Task:
    """A runtime task instance (parsec_task_t analog)."""

    __slots__ = ("taskpool", "task_class", "locals", "data", "output",
                 "priority", "chore_mask", "status", "uid", "repo_entry",
                 "on_complete", "prof", "dsl", "vc")

    def __init__(self, taskpool, task_class, locals: Tuple[int, ...],
                 priority: int = 0):
        self.taskpool = taskpool
        self.task_class = task_class
        self.locals = tuple(locals)
        # per-flow input values, keyed by flow name
        self.data: Dict[str, Any] = {}
        # per-flow output values (filled by completion path)
        self.output: Dict[str, Any] = {}
        self.priority = priority
        self.chore_mask = (1 << 30) - 1
        self.status = TaskStatus.NONE
        self.uid = next(_task_counter)
        self.repo_entry = None
        self.on_complete: Optional[Callable[["Task"], None]] = None
        self.prof: Dict[str, float] = {}
        self.dsl: Dict[str, Any] = {}   # DSL-private state (DTD links, ...)
        # vector clock stamped by the dfsan race sanitizer
        # (analysis/dfsan.py); None whenever the sanitizer is off
        self.vc: Optional[Dict[int, int]] = None

    @property
    def key(self) -> Tuple[int, Tuple[int, ...]]:
        """Unique key inside the taskpool (task_class.make_key analog)."""
        return self.task_class.make_key(self.locals)

    def input_values(self) -> List[Any]:
        return [self.data.get(f.name) for f in self.task_class.flows
                if not f.is_ctl]

    def __repr__(self) -> str:
        args = ", ".join(map(str, self.locals))
        return f"{self.task_class.name}({args})"


def normalize_outputs(result: Any, out_flow_names: Sequence[str],
                      label: Any) -> Dict[str, Any]:
    """Functional-body result → output-flow dict: None = no outputs,
    dict = as-is, tuple/list zipped against the output flows (arity
    checked), a bare value requires exactly one output flow. THE single
    copy of this contract — the device layer and the native DTD engine
    both normalize through here, so engine/device choice can never
    change what a body's return value means. ``label`` is only used in
    error messages (a Task repr, a seq id, ...)."""
    if result is None:
        return {}
    if isinstance(result, dict):
        return result
    if isinstance(result, (tuple, list)):
        if len(result) != len(out_flow_names):
            raise ValueError(
                f"{label}: body returned {len(result)} values for "
                f"{len(out_flow_names)} output flows")
        return dict(zip(out_flow_names, result))
    if len(out_flow_names) != 1:
        raise ValueError(
            f"{label}: single return value but {len(out_flow_names)} "
            "output flows")
    return {out_flow_names[0]: result}
