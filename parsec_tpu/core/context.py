"""Execution context and worker scheduling loop.

Reference mapping:
- ``parsec_init`` (parsec.c:384-924): builds the context — vpmap, execution
  streams (one per core), scheduler selection, device registration — and
  spawns worker threads that block on a barrier until work arrives.
- ``parsec_context_add_taskpool`` (scheduling.c:678-727): installs the
  default termdet, runs the taskpool's startup hook to seed
  no-predecessor tasks, schedules them.
- ``parsec_context_start/test/wait`` (scheduling.c:750-808).
- ``__parsec_context_wait`` (scheduling.c:537-676): the hot worker loop —
  select → prepare input → execute chore → complete → release deps, with
  exponential backoff when starved.
- ``__parsec_task_progress`` (scheduling.c:472-535) incl. the AGAIN path
  (priority demotion + reschedule) and ASYNC (device completes later).
- Release path ``parsec_release_dep_fct`` (parsec.c:1783-1921): successors
  counted down via the taskpool's pending table; ready tasks pushed as a
  priority-sorted ring; the best one is kept as the stream's bypass
  ``next_task`` (scheduling.c:346-398).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import weakref

from .future import DataCopyFuture
from .reshape import resolve_reshape
from .task import HookReturn, Task, TaskStatus
from .taskpool import DataRef, SuccessorRef, Taskpool
from ..utils import debug_history, mca_param
from ..utils.debug import debug_verbose, warning
from .. import termdet as termdet_mod

mca_param.register("runtime.nb_cores", 0, help="worker streams (0 = os.cpu_count())")
mca_param.register("runtime.stage_reads", "auto",
                   help="stage-through collection reads to the "
                        "accelerator: auto (when a non-CPU device is "
                        "registered) | 1 | 0")
mca_param.register("runtime.backoff_min_us", 50, help="starvation backoff floor")
mca_param.register("runtime.backoff_max_us", 2000, help="starvation backoff ceiling")
mca_param.register("runtime.release_batch", 1,
                   help="batch a completed task's dependency releases "
                        "into one striped-lock pass (0 = per-dep locks)")
mca_param.register("runtime.bypass_chain", 1,
                   help="keep a completing task's best ready successor "
                        "in the stream's bypass slot (never queued); "
                        "0 = all ready tasks go through the scheduler")
mca_param.register("runtime.stage_timers", 0,
                   help="accumulate per-stage runtime-overhead timers "
                        "(select/dispatch/release on the streams, insert "
                        "on DTD taskpools) — the taskrate bench's "
                        "overhead breakdown; off by default (hot path)")
mca_param.register("vpmap", "flat",
                   help="virtual-process map: flat | nb:SIZE | "
                        "list:0,0,1,... | file:PATH")
mca_param.register("profiling.dot", "",
                   help="capture the executed DAG to this .dot file at "
                        "fini (--dot flag, parsec.c:589-607 analog)")
mca_param.register("runtime.lineage", 1,
                   help="record (class, coords) of every completed task "
                        "on its taskpool (Taskpool.completed_tasks) — "
                        "the survivors' lineage input for fault "
                        "recovery (data/recovery.py); 0 disables")
mca_param.register("runtime.ckpt_interval", 0,
                   help="checkpoint the registered collections every N "
                        "completed taskpools at quiesce points (see "
                        "Context.enable_checkpoints); 0 = only the "
                        "seconds-based trigger (or off)")
mca_param.register("runtime.ckpt_interval_s", 0.0,
                   help="also checkpoint when this many seconds passed "
                        "since the last save, checked at quiesce "
                        "points; 0 = only the taskpool-count trigger")
mca_param.register("runtime.ckpt_dir", "",
                   help="default directory for Context.enable_checkpoints")


class ExecutionStream:
    """Per-worker execution stream (reference parsec_execution_stream_t)."""

    __slots__ = ("context", "th_id", "vp_id", "sched_obj", "next_task",
                 "thread", "stats", "_vp_peers", "_steal_order", "infos")

    def __init__(self, context: "Context", th_id: int, vp_id: int):
        from ..utils.info import InfoArray, per_stream_infos
        self.context = context
        self.th_id = th_id
        self.vp_id = vp_id
        self.sched_obj = None
        self.next_task: Optional[Task] = None   # priority bypass slot
        self.thread: Optional[threading.Thread] = None
        self.stats = {"executed": 0, "selected": 0, "starved": 0,
                      "stolen": 0,
                      # per-stage overhead timers (runtime.stage_timers)
                      "select_s": 0.0, "select_calls": 0,
                      "dispatch_s": 0.0, "release_s": 0.0}
        self._vp_peers = None        # cached steal orders (sched/base.py)
        self._steal_order = None
        # extensible per-stream info slots (parsec_internal.h:688-702)
        self.infos = InfoArray(per_stream_infos, self)


def _parse_vpmap(nb_cores: int) -> List[int]:
    """Return vp_id per stream (reference vpmap.c:162-368; spec grammar
    in utils/vpmap.py: flat | nb:SIZE | list:... | file:PATH)."""
    from ..utils import vpmap
    return vpmap.parse(str(mca_param.get("vpmap", "flat")), nb_cores)


class Context:
    """The runtime context (parsec_context_t analog)."""

    def __init__(self, nb_cores: Optional[int] = None,
                 scheduler: Optional[str] = None,
                 comm=None):
        from .. import device as device_mod
        from .. import sched as sched_mod
        from ..profiling import pins as pins_mod

        if nb_cores is None or nb_cores <= 0:
            nb_cores = int(mca_param.get("runtime.nb_cores", 0)) or \
                min(os.cpu_count() or 1, 8)
        self.nb_cores = nb_cores
        self.comm = comm            # comm engine (None = single process)
        self.my_rank = comm.rank if comm is not None else 0

        vp_ids = _parse_vpmap(nb_cores)
        self.streams = [ExecutionStream(self, i, vp_ids[i])
                        for i in range(nb_cores)]
        # context-level counters (tasks completed by device managers,
        # which have no owning stream — ASYNC contract)
        self.stats: Dict[str, int] = {"device_completed": 0}

        self.scheduler = sched_mod.new_scheduler(scheduler)
        self.scheduler.install(self)
        for es in self.streams:
            self.scheduler.flow_init(es)

        # release-path knobs, resolved once per context (the hot loops
        # read attributes, not the MCA registry); lowercase so
        # set(..., False) / "OFF" disable like "0" does
        self._release_batch = str(mca_param.get(
            "runtime.release_batch", 1)).lower() not in ("0", "off", "false")
        self._bypass_chain = str(mca_param.get(
            "runtime.bypass_chain", 1)).lower() not in ("0", "off", "false")
        # data-plane broadcast enable (comm.bcast, registered by
        # comm.collectives); resolved once like the release knobs
        self._comm_bcast = str(mca_param.get(
            "comm.bcast", 1)).lower() not in ("0", "off", "false")
        # per-stage overhead timers (select/dispatch/release into
        # es.stats, insert on DTD taskpools); enabled by the MCA param
        # or the profiling `overhead` PINS module
        self.stage_timers = str(mca_param.get(
            "runtime.stage_timers", 0)).lower() not in ("0", "off",
                                                        "false", "")
        # lineage record for fault recovery (runtime.lineage)
        self._track_completed = str(mca_param.get(
            "runtime.lineage", 1)).lower() not in ("0", "off", "false")
        # deterministic failure injection: tick task units on the
        # victim rank (comm.fault_inject_unit = tasks)
        self._fault = getattr(comm, "fault", None)
        # periodic async checkpoints (enable_checkpoints): None = off
        self._ckpt = None

        self.devices = device_mod.Registry(self)
        self.pins = pins_mod.PinsManager(self)

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # native DTD engines (dsl/dtd_native.py): live engines are
        # pumped by the worker loop; terminated pools fold their
        # counters into _ndtd_totals so completed-task totals survive
        self._ndtd_live: List = []
        self._ndtd_lock = threading.Lock()
        self._ndtd_totals: Dict[str, int] = {}
        # per-tenant native completions (the tenant PINS module and the
        # metrics collector fold these in at scrape — native pools never
        # fire the per-task EXEC hooks, by design)
        self._ndtd_tenant_totals: Dict[str, int] = {}
        self._active_taskpools: List[Taskpool] = []
        # name → taskpool, kept past termination: late control traffic
        # (DTD flush writebacks/acks) must still find its taskpool
        self._taskpools_by_name: Dict[str, Taskpool] = {}
        self._aborted: List[Taskpool] = []
        self._started = False
        self._shutdown = False
        self._work_evt = threading.Event()
        self.grapher = None          # profiling.grapher hook
        self.trace = None            # profiling trace hook
        self.serving = None          # serving.runtime.ServingRuntime
        #                              (attached by serving.enable /
        #                              first Context.submit)
        self.dfsan = None            # analysis.dfsan race sanitizer (PINS
        #                              module sets it; None = zero overhead)
        self.kv_state = None         # serving KV state layer (paged
        #                              prefix cache — serving/kv.py
        #                              KVStateLayer attaches itself)
        # PINS modules selected by the `pins` MCA param; must come after
        # trace/grapher init (task_profiler installs a Trace on self.trace)
        from ..profiling import pins_modules as pins_modules_mod
        self.pins_modules = pins_modules_mod.install_selected(self)
        # bounded device residency for task-written collection tiles
        # (device.hbm_budget_mb; reference: GPU LRU eviction lists,
        # device_gpu.h:115-136) — cold device tiles spill back to host
        # numpy through their collection
        from ..device.hbm import manager_from_mca
        self.hbm = manager_from_mca()

        # always-on metrics plane (profiling/metrics.py): the process-
        # global registry plus this context's scrape-time collectors
        # (queue depth, steal rates, wfq pool_stats, tenants, HBM,
        # compile cache). The only HOT-path cost is one sharded counter
        # inc per completed task; profiling.metrics=0 removes even that
        # (the observability bench's A/B baseline).
        from ..profiling import metrics as metrics_mod
        self.metrics = metrics_mod.registry()
        self._metrics_unhook = None
        self._metrics_server = None
        if metrics_mod.enabled():
            self._metrics_unhook = \
                metrics_mod.install_context_collectors(self)
            port = int(mca_param.get("serving.metrics_port", 0))
            if port:
                self._metrics_server = metrics_mod.serve_http(
                    port, statusz_fn=self.statusz)

        self._dot_path = str(mca_param.get("profiling.dot", "") or "")
        if self._dot_path:
            from ..profiling.grapher import Grapher
            Grapher().install(self)     # written out at fini

        if comm is not None and hasattr(comm, "install_activate_handler"):
            comm.install_activate_handler(self)

        for es in self.streams:
            t = threading.Thread(target=self._worker_main, args=(es,),
                                 name=f"parsec-es-{es.th_id}", daemon=True)
            es.thread = t
            t.start()
        debug_verbose(3, "context",
                      "context up: %d streams, sched=%s",
                      nb_cores, self.scheduler.name)

    @property
    def nb_ranks(self) -> int:
        """The CURRENT world size — read through to the comm engine
        (elastic meshes grow/shrink it live; a snapshot taken at
        context construction would route new cross-rank taskpools and
        collections against a stale world)."""
        return self.comm.nb_ranks if self.comm is not None else 1

    # ------------------------------------------------------------------ API
    def add_taskpool(self, tp: Taskpool) -> None:
        """parsec_context_add_taskpool analog (scheduling.c:678-727)."""
        # registration-time static lint (analysis.lint = off|warn|error):
        # with `error`, a taskpool whose flow declarations carry hazards
        # (undeclared producers, WAW, cycles, ...) is refused BEFORE any
        # runtime state is touched (analysis/lint.py HazardError)
        lint_mode = str(mca_param.get("analysis.lint", "off")).lower()
        if lint_mode in ("warn", "error") and tp.task_classes:
            tp.validate(mode=lint_mode)
        if tp.monitor is None:
            tp.monitor = termdet_mod.new_monitor(comm=self.comm)
        tp.monitor.monitor(tp._on_terminated)
        if self.comm is not None and hasattr(self.comm, "register_termdet"):
            self.comm.register_termdet(tp.name, tp.monitor)
        tp.context = self
        if self.comm is not None and self.nb_ranks > 1:
            # expose the taskpool's collections for one-sided tile
            # fetches (CommEngine.fetch_tile): bodies using the
            # direct-memory gathered-operand pattern resolve remote
            # tiles through the owner's comm thread
            g = getattr(tp, "g", None)
            for obj in vars(g).values() if g is not None else ():
                if hasattr(obj, "data_of") and hasattr(obj, "rank_of") \
                        and hasattr(obj, "name"):
                    self.comm.expose_collection(obj, scope=tp.name)
        with self._lock:
            self._active_taskpools.append(tp)
            self._taskpools_by_name[tp.name] = tp
        if self.comm is not None and hasattr(self.comm, "taskpool_registered"):
            # drain parked activations; False = registration refused
            # (broken mesh) — the engine already aborted the pool, so
            # don't launch startup work into a dead mesh
            if self.comm.taskpool_registered(tp) is False:
                return
        if tp.on_enqueue is not None:
            tp.on_enqueue(tp)
        self.pins.taskpool_init(tp)
        startup = tp.startup_hook(tp) or []
        if startup:
            self.schedule(None, list(startup))
        tp.monitor.ready()
        if self._started:
            self._work_evt.set()

    def start(self) -> None:
        """parsec_context_start analog: release the workers."""
        with self._lock:
            self._started = True
        if self.comm is not None:
            self.comm.enable()
        self._work_evt.set()

    @property
    def stage_reads(self) -> bool:
        """True when collection reads should stage-through to the
        accelerator (``runtime.stage_reads``: auto = a real non-CPU
        device is registered). The reference keeps per-device data
        copies with coherency (device_gpu stage-in attaches the GPU
        copy to the data object); here the collection's stored tile is
        REPLACED by its staged device array on first read, so every
        later reader reuses the single H2D transfer — re-staging per
        task measured 100×-class slowdowns on remote-tunnel backends
        where host transfers are synchronous. Set ``0`` for host-pure
        workloads (e.g. wire-latency harnesses: staging would route
        every payload through the accelerator)."""
        # per-read hot path: cache the resolved answer against the MCA
        # registry generation (one int compare) instead of taking the
        # registry lock per collection read
        gen = mca_param.generation()
        cached = self.__dict__.get("_stage_reads_gen")
        if cached is not None and cached[0] == gen:
            return cached[1]
        mode = str(mca_param.get("runtime.stage_reads", "auto"))
        if mode in ("0", "off", "false"):
            result = False
        elif mode in ("1", "on", "true"):
            result = True
        else:
            result = any(
                getattr(d, "platform", "cpu") not in ("cpu",)
                for d in getattr(self.devices, "devices", []))
        self.__dict__["_stage_reads_gen"] = (gen, result)
        return result

    def stage_read(self, dc, key, value):
        """Stage-through one collection read (see :attr:`stage_reads`):
        host arrays are device_put (async) and written back so the
        collection holds the device copy; everything else passes
        through."""
        import numpy as np
        if not self.stage_reads or not isinstance(value, np.ndarray):
            return value
        try:
            import jax
            staged = jax.device_put(value)
        except Exception:  # noqa: BLE001 — staging is an optimization
            return value
        dc.write_tile(key, staged)
        return staged

    def submit(self, tp: Taskpool, tenant=None,
               deadline_s: Optional[float] = None,
               weight: Optional[float] = None,
               rank_scope=None, hbm_bytes: int = 0):
        """Serving-mode taskpool submission: route ``tp`` through the
        multi-tenant serving runtime (admission control, weighted-fair
        scheduling, per-submission deadline with cancellation, tenant
        quarantine, overload shedding) and return a
        :class:`~parsec_tpu.serving.runtime.Submission` handle. A
        runtime with default knobs is attached on first use; call
        :func:`parsec_tpu.serving.enable` first to configure tenants
        and watermarks explicitly. Raises
        :class:`~parsec_tpu.serving.runtime.AdmissionRejected` (window/
        HBM/overload shed) or :class:`~parsec_tpu.serving.runtime.
        TenantQuarantined` instead of parking unboundedly."""
        if self.serving is None:
            from ..serving.runtime import ServingRuntime
            with self._lock:
                # compare-and-set under the context lock: two client
                # threads racing the first submit must share ONE
                # runtime, or tenant windows/quarantines split across
                # two disconnected tenant tables
                if self.serving is None:
                    ServingRuntime(self)     # attaches as self.serving
        return self.serving.submit(tp, tenant=tenant,
                                   deadline_s=deadline_s, weight=weight,
                                   rank_scope=rank_scope,
                                   hbm_bytes=hbm_bytes)

    def test(self) -> bool:
        """parsec_context_test analog: True iff all taskpools completed."""
        with self._lock:
            return len(self._active_taskpools) == 0

    def wait(self, timeout: Optional[float] = None) -> bool:
        """parsec_context_wait analog: block until every enqueued taskpool
        terminated. Returns False on timeout."""
        if not self._started:
            self.start()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._active_taskpools:
                remaining = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                if remaining == 0.0:
                    return False
                self._cv.wait(remaining if remaining is not None else 0.25)
            if self._aborted:
                tp = self._aborted[0]
                self._aborted.clear()
                raise RuntimeError(
                    f"taskpool {tp.name} aborted: {tp.error}") from tp.error
        return True

    # -------------------------------------------------- native DTD engines
    def _ndtd_register(self, eng) -> None:
        with self._ndtd_lock:
            if eng not in self._ndtd_live:
                self._ndtd_live.append(eng)

    def _ndtd_retire(self, eng) -> None:
        """A pool terminated: fold its engine now if drained, else mark
        it retiring — the workers keep pumping it (cancelled pools drop
        their queued tasks at select time there) and the pump folds it
        once the last in-flight task leaves."""
        if eng.inflight() == 0:
            self._ndtd_unregister(eng)
        else:
            # the pump folds this engine AFTER the pool's termination
            # barrier has advanced the sanitizer base — snapshot the
            # pre-barrier base now so the dfsan replay seeds from it
            san = getattr(eng, "_dfsan", None)
            if san is not None:
                eng._dfsan_base = san.base_snapshot()
            eng.retiring = True

    def _ndtd_unregister(self, eng) -> None:
        """Fold a retired engine's monotonic counters into the context
        totals (idempotent — refired termination is absorbed)."""
        with self._ndtd_lock:
            if eng not in self._ndtd_live:
                return
            self._ndtd_live.remove(eng)
            stats = eng.stats()
            for k, v in stats.items():
                if k in ("inflight", "ready", "obs_ring_depth"):
                    continue                    # gauges, not counters
                if k == "ring_highwater":
                    self._ndtd_totals[k] = max(
                        self._ndtd_totals.get(k, 0), v)
                elif k == "lock_pairs":
                    # acquisition-pair BITMASK (ISSUE 14): OR, not sum
                    self._ndtd_totals[k] = \
                        self._ndtd_totals.get(k, 0) | v
                else:
                    self._ndtd_totals[k] = \
                        self._ndtd_totals.get(k, 0) + v
            ten = getattr(eng.tp, "tenant_name", None) or "(untenanted)"
            self._ndtd_tenant_totals[ten] = \
                self._ndtd_tenant_totals.get(ten, 0) + \
                stats.get("completed_native", 0) + \
                stats.get("completed_python", 0)
        # freeze the trace adapter's ring snapshot + free the C rings
        # BEFORE dropping the per-task refs (the adapter keeps only the
        # raw record arrays — expansion stays deferred to dump time)
        obs_retire = getattr(eng, "obs_retire", None)
        if obs_retire is not None:
            obs_retire()
        eng.release_refs()

    def native_dtd_stats(self) -> Dict[str, int]:
        """Aggregate native-DTD engine counters: retired pools' folded
        totals plus every live engine (scrape-time; the hot loop only
        touches C++ atomics)."""
        with self._ndtd_lock:
            out = dict(self._ndtd_totals)
            live = list(self._ndtd_live)
        for eng in live:
            for k, v in eng.stats().items():
                if k == "ring_highwater":
                    out[k] = max(out.get(k, 0), v)
                elif k == "lock_pairs":
                    out[k] = out.get(k, 0) | v
                else:
                    out[k] = out.get(k, 0) + v
        return out

    def native_tenant_stats(self) -> Dict[str, int]:
        """Per-tenant native-engine completions (retired pools' folded
        totals plus live engines): the scrape-time source the tenant
        PINS module and the metrics collector merge, since native pools
        never fire the per-task EXEC hooks."""
        with self._ndtd_lock:
            out = dict(self._ndtd_tenant_totals)
            live = list(self._ndtd_live)
        for eng in live:
            st = eng.stats()
            ten = getattr(eng.tp, "tenant_name", None) or "(untenanted)"
            out[ten] = out.get(ten, 0) + \
                st.get("completed_native", 0) + \
                st.get("completed_python", 0)
        return out

    def _ndtd_pump(self, es: "ExecutionStream") -> bool:
        """Progress the live native DTD engines on this worker; True
        when any task completed (native-bodied ones inside the C call
        with the GIL released, Python-bodied ones here). Exception-
        guarded like _task_progress: a raising user hook (on_retire /
        on_complete) aborts ITS pool instead of killing the worker."""
        with self._ndtd_lock:
            engines = list(self._ndtd_live)
        ran = False
        for eng in engines:
            try:
                if eng.pump(es):
                    ran = True
            except Exception as exc:  # noqa: BLE001 — worker must survive
                warning("scheduling", "native DTD pump of %s raised: %s",
                        eng.tp.name, exc)
                import traceback
                traceback.print_exc()
                eng.tp.abort(exc)
                ran = True
        return ran

    # ------------------------------------------------------ observability
    def statusz(self) -> Dict:
        """Live runtime status as one JSON-able dict: the metrics
        registry, stream counters, active pools, and (when serving) the
        tenant/pool report — the /statusz payload of the metrics
        listener (``serving.metrics_port``)."""
        with self._lock:
            active = [tp.name for tp in self._active_taskpools]
        out = {
            "rank": self.my_rank,
            "nb_ranks": self.nb_ranks,
            "scheduler": self.scheduler.name,
            "active_taskpools": active,
            "streams": {es.th_id: dict(es.stats) for es in self.streams},
            "metrics": self.metrics.to_dict(),
        }
        if self.serving is not None:
            out["serving"] = self.serving.report()
        if self.kv_state is not None:
            # KV state plane (pages in use / hit rate / spec counters)
            # — scrape-time snapshot, the autoscaler's KV-pressure row
            out["kv"] = self.kv_state.snapshot()
        out["capacity"] = self._capacity_block()
        if self.trace is not None:
            out["trace_dropped"] = self.trace.dropped()
            # the native-ring share separately: a truncated NATIVE
            # capture (in-engine ring wrap / evicted snapshot) must be
            # loud on its own row, not hidden in the Python-ring total
            out["trace_native_dropped"] = self.trace.native_dropped()
        nstats = self.native_dtd_stats()
        if nstats:
            out["native_dtd"] = nstats
        return out

    def _capacity_block(self) -> Dict:
        """The statusz ``capacity`` block: configured vs live world
        size, a per-rank role map (self/joined/draining/departed/dead),
        and — when an elastic controller is attached — the autoscaler's
        desired count, last decision, and remaining cooldown. The
        operator's view of elasticity state without running the bench."""
        comm = self.comm
        if comm is not None and hasattr(comm, "world_status"):
            ws = comm.world_status()
        else:
            ws = {"configured": self.nb_ranks, "world": self.nb_ranks,
                  "live": list(range(self.nb_ranks)), "departed": [],
                  "dead": []}
        departed = set(ws.get("departed") or ())
        dead = set(ws.get("dead") or ())
        el = getattr(self.serving, "elastic", None) \
            if self.serving is not None else None
        draining = set(el.draining_ranks()) if el is not None else set()
        roles = {}
        for r in range(int(ws.get("world", self.nb_ranks))):
            if r == self.my_rank:
                roles[r] = "self"
            elif r in dead:
                roles[r] = "dead"
            elif r in departed:
                roles[r] = "departed"
            elif r in draining:
                roles[r] = "draining"
            else:
                roles[r] = "joined"
        out = {"configured_world": ws.get("configured"),
               "world": ws.get("world"),
               "live_world": len(ws.get("live") or ()),
               "roles": roles}
        if el is not None:
            out["autoscaler"] = el.status()
        return out

    def metrics_text(self) -> str:
        """Prometheus text exposition of the metrics registry (the
        /metrics payload)."""
        return self.metrics.to_prometheus_text()

    def fini(self) -> None:
        """parsec_fini analog: drain and stop the workers."""
        if self._metrics_server is not None:
            self._metrics_server.shutdown()
            self._metrics_server = None
        if self._metrics_unhook is not None:
            self._metrics_unhook()
            self._metrics_unhook = None
        if self.serving is not None:
            self.serving.shutdown()
        if self._ckpt is not None:
            # let an in-flight async save land — a torn final step would
            # be discarded by the atomic protocol, but the work is paid
            self._ckpt.wait(timeout=30.0)
        with self._lock:
            self._shutdown = True
        self._work_evt.set()
        for es in self.streams:
            if es.thread is not None:
                es.thread.join(timeout=5.0)
        for dev in self.devices.devices:
            dev.shutdown()
        if self.comm is not None:
            self.comm.disable()
        self.scheduler.remove(self)
        if self._dot_path and self.grapher is not None:
            try:
                self.grapher.write(self._dot_path)
            except OSError as exc:
                warning("profiling", "could not write %s: %s",
                        self._dot_path, exc)
        # MCA-selected PINS modules report at component close then detach
        # (reference modules print their data in their _fini)
        from ..utils.debug import get_verbosity
        for mod in self.pins_modules:
            if get_verbosity() >= 2:    # report() can scan the full trace
                debug_verbose(2, "pins", "%s: %s", mod.name, mod.report())
            mod.uninstall()
        debug_verbose(3, "context", "context down; stats=%s",
                      {es.th_id: es.stats for es in self.streams})

    # --------------------------------------------------------- scheduling
    def schedule(self, es: Optional[ExecutionStream], tasks: Sequence[Task],
                 distance: int = 0) -> None:
        """__parsec_schedule analog: push a ring of ready tasks."""
        if not tasks:
            return
        for t in tasks:
            t.status = TaskStatus.NONE
        self.pins.select_begin(es, tasks)
        if len(tasks) > 1:
            tasks = sorted(tasks, key=lambda t: -t.priority)
        self.scheduler.schedule(es, tasks, distance)
        # is_set() is a plain bool read; while workers are busy the event
        # stays set, so the common completion path skips the heavier
        # set() (lock + notify). A worker that cleared it re-selects
        # BEFORE waiting (see _worker_main), so this can't lose a wakeup.
        evt = self._work_evt
        if not evt.is_set():
            evt.set()

    def find_taskpool(self, name: str, active_only: bool = True):
        """Lookup by name; ``active_only=False`` includes terminated pools
        (control traffic like DTD flush outlives termination)."""
        with self._lock:
            if active_only:
                return next((t for t in self._active_taskpools
                             if t.name == name), None)
            return self._taskpools_by_name.get(name)

    def _taskpool_terminated(self, tp: Taskpool) -> None:
        if self.dfsan is not None:
            # termdet is a full synchronization point: everything the
            # pool did happens-before whatever runs next (keeps the
            # sanitizer race-free across sequentially-run taskpools)
            self.dfsan.barrier()
        with self._cv:
            try:
                self._active_taskpools.remove(tp)
            except ValueError:
                pass
            if tp.error is not None and tp not in self._aborted and \
                    not getattr(tp, "error_owned", False):
                # error_owned: the serving runtime reports this pool's
                # failure to ITS submitter (quarantine + Submission.wait)
                # — a failed tenant must not poison an unrelated
                # caller's Context.wait
                self._aborted.append(tp)
            quiesced = not self._active_taskpools
            self._cv.notify_all()
        if self.hbm is not None:
            # entries whose collection died with its taskpool: free the
            # accounting, skip the pointless spill
            self.hbm.sweep(_hbm_entry_dead)
        if quiesced and tp.error is None and self._ckpt is not None:
            self._ckpt.quiesce_point()

    # ------------------------------------------------- async checkpoints
    def enable_checkpoints(self, collections: Dict[str, object],
                           directory: Optional[str] = None,
                           interval: Optional[int] = None,
                           interval_s: Optional[float] = None):
        """Register ``collections`` (``{name: DataCollection}``) for
        periodic asynchronous checkpoints: at each QUIESCE point (the
        last active taskpool terminating cleanly — all state lives in
        the collections, the model data/checkpoint.py documents), if
        ``interval`` completed taskpools or ``interval_s`` seconds have
        passed since the last save, this rank's local tile references
        are captured synchronously (write_tile replaces references, so
        the captured cut is consistent) and serialized to disk on a
        background saver thread with the Orbax-style atomic-rename
        protocol. Defaults come from ``runtime.ckpt_interval``/
        ``runtime.ckpt_interval_s``/``runtime.ckpt_dir``. Returns the
        underlying :class:`~parsec_tpu.data.checkpoint.CheckpointManager`.
        """
        from ..data.checkpoint import CheckpointManager
        directory = directory or str(mca_param.get("runtime.ckpt_dir", ""))
        if not directory:
            raise ValueError("enable_checkpoints: no directory (argument "
                             "or runtime.ckpt_dir)")
        if interval is None:
            interval = int(mca_param.get("runtime.ckpt_interval", 0))
        if interval_s is None:
            interval_s = float(mca_param.get("runtime.ckpt_interval_s",
                                             0.0))
        mgr = CheckpointManager(directory, my_rank=self.my_rank,
                                nb_ranks=self.nb_ranks)
        self._ckpt = _CkptState(mgr, dict(collections), interval,
                                interval_s)
        return mgr

    def checkpoint_wait(self, timeout: Optional[float] = None) -> bool:
        """Join the in-flight background checkpoint save, if any (tests
        and pre-shutdown flushes). True when no save is pending."""
        return self._ckpt.wait(timeout) if self._ckpt is not None else True

    def checkpoint_now(self) -> Optional[str]:
        """Force a synchronous checkpoint of the registered collections
        (caller guarantees quiesce). Returns the step directory."""
        return self._ckpt.save_now() if self._ckpt is not None else None

    # --------------------------------------------------------- worker loop
    def _worker_main(self, es: ExecutionStream) -> None:
        from ..utils import binding
        binding.bind_worker(es.th_id)     # best-effort (-b analog)
        backoff_min = int(mca_param.get("runtime.backoff_min_us", 50)) / 1e6
        backoff_max = int(mca_param.get("runtime.backoff_max_us", 2000)) / 1e6
        backoff = backoff_min
        while True:
            if self._shutdown:
                return
            # retiring native engines (aborted pool already removed
            # from _active_taskpools, tasks still draining) count as
            # work: without them in this condition the cancelled tasks
            # would never be dropped and the engine never folded
            if not self._started or not (self._active_taskpools or
                                         self._ndtd_live):
                self._work_evt.clear()
                # re-check after clear to avoid a lost wakeup from
                # add_taskpool()/start() racing with the clear
                if self._shutdown or (self._started and
                                      (self._active_taskpools or
                                       self._ndtd_live)):
                    continue
                self._work_evt.wait(timeout=0.1)
                continue
            task = es.next_task
            es.next_task = None
            if task is None:
                if self.stage_timers:
                    t0 = time.perf_counter()
                    task = self.scheduler.select(es)
                    es.stats["select_s"] += time.perf_counter() - t0
                    es.stats["select_calls"] += 1
                else:
                    task = self.scheduler.select(es)
            if task is None and self._ndtd_live:
                # native DTD pump (the insert→release loop behind the C
                # ABI): native-bodied tasks drain entirely inside the
                # ctypes call with the GIL released; Python-bodied ones
                # run here. Tried when the Python queues are dry so
                # queued Python pools are never starved by a native loop.
                if self._ndtd_pump(es):
                    backoff = backoff_min
                    continue
            if task is None:
                es.stats["starved"] += 1
                # event-driven wakeup: schedule() sets _work_evt, so a
                # starved worker parks until new work instead of sleeping
                # through the latency path (the reference wakes workers
                # from remote_dep delivery the same way). Clear-then-
                # reselect avoids the lost-wakeup race; the timeout only
                # bounds termdet/shutdown polling.
                self._work_evt.clear()
                task = self.scheduler.select(es)
                if task is None and self._ndtd_live and \
                        self._ndtd_pump(es):
                    # a native batch armed between the pump above and
                    # the clear: same lost-wakeup guard as the reselect
                    backoff = backoff_min
                    continue
                if task is None:
                    self._work_evt.wait(timeout=backoff)
                    backoff = min(backoff * 2, backoff_max)
                    continue
            backoff = backoff_min
            if task.taskpool.cancelled:
                # cancelled pool (deadline expiry / Submission.cancel):
                # drop instead of executing — covers the bypass slot and
                # every scheduler; the decrement keeps the idempotent
                # termdet counters consistent (a cancelled pool already
                # force-terminated, refires are absorbed)
                task.taskpool.addto_nb_tasks(-1)
                continue
            es.stats["selected"] += 1
            try:
                self._task_progress(es, task)
            except Exception as exc:  # noqa: BLE001 - worker must survive
                warning("scheduling", "task %r raised: %s", task, exc)
                import traceback
                traceback.print_exc()
                from ..utils import debug_history
                debug_history.dump_on_fatal(f"task {task!r} raised")
                # successors can never fire: abort the pool so waiters are
                # released with the error instead of hanging (parsec_abort)
                task.taskpool.abort(exc)

    def _task_progress(self, es: ExecutionStream, task: Task) -> None:
        """__parsec_task_progress analog (scheduling.c:472-535)."""
        tp = task.taskpool
        tc = task.task_class
        # prepare_input (generated data_lookup analog): resolve inputs not
        # attached by the release path (collection reads of startup tasks)
        task.status = TaskStatus.PREPARE_INPUT
        t0 = time.perf_counter() if (self.stage_timers and es is not None) \
            else None
        lookup = getattr(tc, "data_lookup", None)
        if lookup is not None:
            self.pins.prepare_input_begin(es, task)
            lookup(task)
            self.pins.prepare_input_end(es, task)
        # execute: walk incarnations honoring the chore mask
        task.status = TaskStatus.HOOK
        self.pins.exec_begin(es, task)
        rc = self._execute(es, task)
        if t0 is not None:
            # dispatch = prepare_input + incarnation walk + hook call
            # (for a null body this IS the per-task dispatch overhead)
            es.stats["dispatch_s"] += time.perf_counter() - t0
        if rc == HookReturn.ASYNC:
            return                      # device layer completes it later
        if rc == HookReturn.AGAIN:
            task.priority -= 1          # priority demotion + reschedule
            self.schedule(es, [task], distance=1)
            return
        if rc == HookReturn.ERROR:
            raise RuntimeError(f"all incarnations of {task!r} failed")
        self.complete_task(es, task)

    def _execute(self, es: ExecutionStream, task: Task) -> HookReturn:
        """__parsec_execute analog (scheduling.c:124-203): try incarnations
        in declaration order, skipping masked/vetoed ones."""
        tc = task.task_class
        if debug_history.enabled():     # DEBUG_MARK_EXE analog
            debug_history.mark("EXE %s%r es=%s", tc.name,
                               tuple(task.locals),
                               getattr(es, "th_id", -1))
        for i, chore in enumerate(tc.incarnations):
            if not (task.chore_mask & (1 << i)):
                continue
            if chore.evaluate is not None and not chore.evaluate(task):
                continue
            dev = self.devices.device_for(chore.device_type, task)
            if dev is None:
                continue
            rc = None
            try:
                rc = dev.execute(es, task, chore)
            finally:
                if rc != HookReturn.ASYNC:
                    # async devices keep their in-flight unit until the
                    # manager completes the task (release_load); every
                    # other outcome — including a raising hook — must
                    # release here or the device leaks load forever
                    dev.release_load()
            if rc == HookReturn.NEXT:
                task.chore_mask &= ~(1 << i)
                continue
            return rc
        return HookReturn.ERROR

    def _hbm_track(self, dc, key, value):
        """Register a device-resident tile a task is writing to its
        collection; over budget, the manager spills the coldest tracked
        tile back into its collection as host numpy. Called BEFORE the
        collection write with the entry PINNED (caller unpins after the
        write): the manager always holds the newest version AND cannot
        evict it inside the track→write window, where the spill's host
        write would race the device write (budget under-enforcement).
        Returns the key to unpin, or None when untracked."""
        from ..device.hbm import track_collection_write
        return track_collection_write(self.hbm, dc, key, value)

    def complete_task(self, es: Optional[ExecutionStream], task: Task) -> None:
        """__parsec_complete_execution + release_deps analog
        (scheduling.c:441-470, parsec.c:1694-1921)."""
        task.status = TaskStatus.COMPLETE
        tp = task.taskpool
        tc = task.task_class
        if es is not None:
            es.stats["executed"] += 1
        else:
            # device-manager completion (ASYNC contract): attribute
            # here so TASKS_EXECUTED still covers every task
            with self._lock:
                self.stats["device_completed"] = \
                    self.stats.get("device_completed", 0) + 1
        self.pins.exec_end(es, task)
        self.pins.complete_exec_begin(es, task)
        if self.trace is not None:
            self.trace.task_complete(task)
        if self.grapher is not None:
            self.grapher.task_executed(task)

        self.pins.release_deps_begin(es, task)
        t_rel = time.perf_counter() if (self.stage_timers and
                                        es is not None) else None
        ready: List[Task] = []
        # local refs accumulate and release in ONE striped-lock batch
        # (runtime.release_batch; parsec_release_dep_fct walks its
        # ready-ring the same way) instead of a lock pair per dep
        local_refs: List[SuccessorRef] = []
        # remote deps sharing one produced value ship the payload ONCE
        # per rank (the reference's one-data-per-(dep, rank) aggregation,
        # remote_dep.c) — grouped per VALUE here so the engine can also
        # tree-route a value with consumers on >=2 ranks down a
        # broadcast topology (remote_dep_broadcast) instead of paying
        # one root egress per rank
        remote_groups: Optional[Dict[int, Dict[int, List]]] = \
            {} if self.nb_ranks > 1 else None
        san = self.dfsan
        grapher = self.grapher
        for ref in tc.iterate_successors(task):
            if isinstance(ref, DataRef):
                # track (pinned) first, write second, unpin last — see
                # _hbm_track
                mkey = None
                if self.hbm is not None:
                    mkey = self._hbm_track(ref.collection, ref.key,
                                           ref.value)
                if san is not None:
                    # stamp the committed version BEFORE it lands so a
                    # racing reader's check sees the writer's clock
                    san.observe_write(task, ref.collection, ref.key)
                ref.collection.write_tile(ref.key, ref.value)
                if mkey is not None:
                    self.hbm.unpin(mkey)
                continue
            if san is not None:
                # happens-before edge task -> successor, observed BEFORE
                # the dep is counted (the successor may run immediately)
                san.observe_edge(task, ref)
            if grapher is not None:
                grapher.dep_edge(task, ref.task_class, ref.locals,
                                 ref.flow_name)
            if ref.reshape_spec is not None or \
                    isinstance(ref.value, DataCopyFuture):
                # reshape promise: one shared conversion per layout
                # (parsec_local_reshape analog, runs on this compute
                # thread; remote consumers get the converted value)
                ref.value = resolve_reshape(ref.value, ref.reshape_spec)
                ref.reshape_spec = None
            if remote_groups is not None:
                target_rank = ref.task_class.affinity_rank(ref.locals) \
                    if hasattr(ref.task_class, "affinity_rank") else self.my_rank
                if target_rank != self.my_rank:
                    remote_groups.setdefault(
                        id(ref.value), {}).setdefault(
                            target_rank, []).append(ref)
                    continue
            if self._release_batch:
                local_refs.append(ref)
            else:
                new_task = tp.activate_dep(ref)
                if new_task is not None:
                    ready.append(new_task)
        if local_refs:
            ready.extend(tp.activate_deps(local_refs))
        if remote_groups:
            for _vid, rank_refs in remote_groups.items():
                first = next(iter(rank_refs.values()))[0]
                if self._comm_bcast and len(rank_refs) >= 2 and \
                        first.value is not None:
                    # one value, consumers on >=2 ranks: tree-routed
                    # broadcast (payload leaves this rank once per tree
                    # edge, not once per consumer rank)
                    self.comm.remote_dep_broadcast(task, rank_refs)
                else:
                    for target_rank, refs in rank_refs.items():
                        self.comm.remote_dep_activate_multi(
                            task, target_rank, refs)
        if self._track_completed:
            # lineage record: survivors report these after a peer death
            # so replay recomputes only the unfinished sub-DAG
            tp.completed_tasks.add((tc.name, tuple(task.locals)))
        if self._fault is not None:
            self._fault.on_task_complete()   # injected failure point
        if tc.on_complete is not None:
            tc.on_complete(task)
        if task.on_complete is not None:
            task.on_complete(task)
        if ready and self.trace is not None:
            # causal parent of everything this completion released: the
            # local dependency edges of the request span tree (wire
            # edges are parented by the comm engine's _span_recv). The
            # ready→select queue-wait stamp (q_us on the released
            # task's begin event) shares this loop — one perf_counter,
            # no separate pass in schedule().
            b = task.prof.get("b")      # (span id, t0, stream) — the
            if b is not None:           # trace hook's fused begin stamp
                sid = b[0]
                rid = task.prof.get("rid")
                now = time.perf_counter()
                for t in ready:
                    p = t.prof
                    p["parent_span"] = sid
                    p["q_t0"] = now
                    if rid is not None:
                        p["rid"] = rid
        if ready:
            if self._bypass_chain and es is not None and \
                    es.next_task is None:
                # bypass-slot chaining: the completing task's best
                # successor never touches the queues — the worker loop
                # runs it next (scheduling.c:346-398). max() takes the
                # FIRST maximal task, matching the old stable
                # sort+pop(0) tie-break exactly.
                best = max(ready, key=lambda t: t.priority)
                ready.remove(best)
                es.next_task = best
            if ready:
                self.schedule(es, ready)
        if t_rel is not None:
            es.stats["release_s"] += time.perf_counter() - t_rel
        self.pins.release_deps_end(es, task)
        self.pins.complete_exec_end(es, task)
        # the always-on metrics plane adds NO hot-path work here: the
        # per-stream es.stats["executed"] counters above already exist,
        # and the registry exports their sum as
        # parsec_tasks_completed_total at SCRAPE time (collector)
        tp.addto_nb_tasks(-1)
        # no task mempool here BY MEASUREMENT (round 5, PARITY
        # "Mempools" row): completed tasks die young via refcounting
        # (~0.7 µs/task); a prototyped per-thread freelist measured
        # BREAK-EVEN warm (0.94 µs pop+reset) and cannot reduce the
        # live-object count that drives GC pressure in startup bursts.
        # The reference's mempool.c amortizes C malloc, which CPython's
        # refcounting already covers. Native-path tasks use pmempool_*.


class _SnapshotCollection:
    """A frozen (key → value-reference) cut of one collection, captured
    synchronously at a quiesce point; quacks enough like a
    DataCollection for CheckpointManager.save to serialize it from the
    background saver thread."""

    def __init__(self, items: Dict):
        self._items = items

    def keys(self):
        return list(self._items)

    def is_local(self, _key) -> bool:
        return True         # pre-filtered at capture

    def data_of(self, key):
        return self._items[key]


class _CkptState:
    """Per-context periodic-checkpoint driver (Context.enable_checkpoints).

    Reference capture is synchronous (cheap: ``write_tile`` REPLACES
    tile references rather than mutating arrays, so holding the old
    references is a consistent cut even while the next taskpool runs);
    serialization runs on a daemon saver thread using the atomic-rename
    protocol, so a crash mid-save never corrupts the latest durable
    step. If the saver is still busy at the next due point the save is
    skipped with a warning (the async saver falling behind must not
    stall the runtime)."""

    def __init__(self, mgr, collections: Dict, interval: int,
                 interval_s: float, keep: int = 2):
        self.mgr = mgr
        self.collections = collections
        self.interval = int(interval)
        self.interval_s = float(interval_s)
        self.keep = keep
        self.pools_done = 0
        self._last_pools = 0
        self._last_t = time.monotonic()
        self.saves = 0
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def _capture(self) -> Dict[str, _SnapshotCollection]:
        snap = {}
        for name, dc in self.collections.items():
            items = {}
            for key in dc.keys():
                if hasattr(dc, "is_local") and not dc.is_local(key):
                    continue
                val = dc.data_of(key)
                if val is not None:
                    items[key] = val
            snap[name] = _SnapshotCollection(items)
        return snap

    def _save(self, step: int, snap: Dict) -> Optional[str]:
        try:
            path = self.mgr.save(step, snap,
                                 meta={"pools_done": step})
            self.saves += 1
            if self.keep:
                self.mgr.prune(keep=self.keep)
            return path
        except Exception as exc:  # noqa: BLE001 — saver must not kill
            warning("checkpoint", "async save of step %d failed: %s",
                    step, exc)
            return None

    def quiesce_point(self) -> None:
        with self._lock:
            self.pools_done += 1
            due = (self.interval > 0 and
                   self.pools_done - self._last_pools >= self.interval)
            if not due and self.interval_s > 0:
                due = time.monotonic() - self._last_t >= self.interval_s
            if not due:
                return
            if self._thread is not None and self._thread.is_alive():
                warning("checkpoint", "saver still writing step at "
                        "quiesce %d — skipping this interval",
                        self.pools_done)
                return
            step = self.pools_done
            snap = self._capture()       # synchronous: consistent cut
            self._last_pools = self.pools_done
            self._last_t = time.monotonic()
            t = threading.Thread(target=self._save, args=(step, snap),
                                 name="parsec-ckpt", daemon=True)
            self._thread = t
            t.start()

    def save_now(self) -> Optional[str]:
        with self._lock:
            t = self._thread
        if t is not None:
            t.join()
        with self._lock:
            step = max(self.pools_done, 1)
            snap = self._capture()
            self._last_pools = self.pools_done
            self._last_t = time.monotonic()
        return self._save(step, snap)

    def wait(self, timeout: Optional[float] = None) -> bool:
        with self._lock:
            t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()


def _hbm_entry_dead(_key, entry) -> bool:
    """True when a context-tracked HBM entry's collection weakref (the
    first weakref default of its spill closure) is dead."""
    spill = entry.get("spill")
    for d in getattr(spill, "__defaults__", None) or ():
        if isinstance(d, weakref.ref):
            return d() is None
    return False


def init(nb_cores: Optional[int] = None, scheduler: Optional[str] = None,
         comm=None, argv: Optional[Sequence[str]] = None) -> Context:
    """parsec_init analog. ``argv`` (if given) is parsed for runtime
    options (--mca/--cores/--vpmap/--sched/...; parsec.c:411-463) before
    the context is built; leftover arguments are stored on
    ``context.argv_rest``."""
    rest = None
    if argv is not None:
        from ..utils import cmd_line
        rest = cmd_line.parse(list(argv))
    ctx = Context(nb_cores=nb_cores, scheduler=scheduler, comm=comm)
    ctx.argv_rest = rest
    return ctx


def fini(context: Context) -> None:
    """parsec_fini analog."""
    context.fini()
