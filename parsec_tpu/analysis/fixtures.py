"""Lint fixtures: intentionally-broken PTG taskpools.

Each builder returns a taskpool seeded with exactly one class of bug the
lint must catch (plus a clean control).  The CLI's ``--self-check`` mode
asserts every fixture is flagged with an actionable message naming the
task class, flow and coordinates; ``examples/ex08_lint_hazards.py``
walks the same fixtures interactively.  The racy fixture carries real
bodies so the runtime race sanitizer (analysis/dfsan.py) can execute it
and observe the same hazard dynamically.
"""

from __future__ import annotations

from typing import Tuple

from ..data.collection import LocalCollection
from ..dsl import ptg

#: fixture name -> (builder, rules the lint MUST report for it)
FIXTURES = {}


def _fixture(rules):
    def deco(fn):
        FIXTURES[fn.__name__.replace("build_", "")] = (fn, tuple(rules))
        return fn
    return deco


def _store(n: int = 4) -> LocalCollection:
    return LocalCollection("S", {(i,): float(i) for i in range(n)})


@_fixture(rules=())
def build_clean() -> ptg.Taskpool:
    """Control: a well-formed 4-deep chain — zero findings expected."""
    tp = ptg.Taskpool("clean", N=4, S=_store(1))
    tp.task_class(
        "T", params=("i",),
        space=lambda g: ((i,) for i in range(g.N)),
        flows=[ptg.FlowSpec(
            "X", ptg.RW,
            ins=[ptg.In(data=lambda g, i: (g.S, (0,)),
                        guard=lambda g, i: i == 0),
                 ptg.In(src=("T", lambda g, i: (i - 1,), "X"),
                        guard=lambda g, i: i > 0)],
            outs=[ptg.Out(dst=("T", lambda g, i: (i + 1,), "X"),
                          guard=lambda g, i: i < g.N - 1),
                  ptg.Out(data=lambda g, i: (g.S, (0,)),
                          guard=lambda g, i: i == g.N - 1)])])
    return tp


@_fixture(rules=("waw-hazard", "war-hazard"))
def build_racy() -> ptg.Taskpool:
    """Two independent task classes both write tile S(0,) and a third
    reads it, with no dependency edges at all: a WAW hazard between the
    writers and read/write hazards against the reader.  Bodies are real
    so the fixture also runs under the dfsan sanitizer, which must
    observe the same races dynamically."""
    tp = ptg.Taskpool("racy", S=_store(1))
    W1 = tp.task_class(
        "W1", params=("i",), space=lambda g: ((0,),),
        flows=[ptg.FlowSpec(
            "X", ptg.RW,
            ins=[ptg.In(data=lambda g, i: (g.S, (0,)))],
            outs=[ptg.Out(data=lambda g, i: (g.S, (0,)))])])
    W2 = tp.task_class(
        "W2", params=("i",), space=lambda g: ((0,),),
        flows=[ptg.FlowSpec(
            "X", ptg.RW,
            ins=[ptg.In(data=lambda g, i: (g.S, (0,)))],
            outs=[ptg.Out(data=lambda g, i: (g.S, (0,)))])])
    R = tp.task_class(
        "R", params=("i",), space=lambda g: ((0,),),
        flows=[ptg.FlowSpec(
            "X", ptg.READ,
            ins=[ptg.In(data=lambda g, i: (g.S, (0,)))])])

    @W1.body
    def w1_body(task, x):
        return x + 1.0

    @W2.body
    def w2_body(task, x):
        return x + 10.0

    @R.body
    def r_body(task, x):
        return None
    return tp


@_fixture(rules=("cycle",))
def build_cyclic() -> ptg.Taskpool:
    """P(0) feeds Q(0) feeds P(0): a dependency cycle — neither task can
    ever reach its deps goal, so the taskpool would hang at runtime.
    Both sides declare their producers, so ONLY the cycle rule fires."""
    tp = ptg.Taskpool("cyclic", S=_store(1))
    tp.task_class(
        "P", params=("i",), space=lambda g: ((0,),),
        flows=[ptg.FlowSpec(
            "X", ptg.RW,
            ins=[ptg.In(src=("Q", lambda g, i: (i,), "Y"))],
            outs=[ptg.Out(dst=("Q", lambda g, i: (i,), "Y"))])])
    tp.task_class(
        "Q", params=("i",), space=lambda g: ((0,),),
        flows=[ptg.FlowSpec(
            "Y", ptg.RW,
            ins=[ptg.In(src=("P", lambda g, i: (i,), "X"))],
            outs=[ptg.Out(dst=("P", lambda g, i: (i,), "X"))])])
    return tp


@_fixture(rules=("undeclared-producer",))
def build_undeclared_producer() -> ptg.Taskpool:
    """C(0) declares ``<- X P(0)`` but P's flow X only writes back to the
    collection — it never emits to C, so C's dep can never be satisfied
    (a silent runtime hang without the lint)."""
    tp = ptg.Taskpool("undeclared", S=_store(2))
    tp.task_class(
        "P", params=("i",), space=lambda g: ((0,),),
        flows=[ptg.FlowSpec(
            "X", ptg.RW,
            ins=[ptg.In(data=lambda g, i: (g.S, (0,)))],
            outs=[ptg.Out(data=lambda g, i: (g.S, (0,)))])])
    tp.task_class(
        "C", params=("i",), space=lambda g: ((0,),),
        flows=[ptg.FlowSpec(
            "X", ptg.READ,
            ins=[ptg.In(src=("P", lambda g, i: (i,), "X"))])])
    return tp


@_fixture(rules=("access-violation",))
def build_access_violation() -> ptg.Taskpool:
    """A READ flow with a terminal collection write-back and a CTL flow
    carrying a data input — both contradict the declared FlowAccess
    (only WRITE/RW flows are output flows, core/task.py)."""
    tp = ptg.Taskpool("badaccess", S=_store(2))
    tp.task_class(
        "T", params=("i",), space=lambda g: ((0,),),
        flows=[
            ptg.FlowSpec(
                "X", ptg.READ,
                ins=[ptg.In(data=lambda g, i: (g.S, (0,)))],
                outs=[ptg.Out(data=lambda g, i: (g.S, (0,)))]),
            ptg.FlowSpec(
                "K", ptg.CTL,
                ins=[ptg.In(data=lambda g, i: (g.S, (1,)))]),
        ])
    return tp


@_fixture(rules=("phantom-target",))
def build_phantom_target() -> ptg.Taskpool:
    """T(i) feeds T(i+1) without bounding the range: the last instance
    aims at a task outside the class space."""
    tp = ptg.Taskpool("phantom", N=3, S=_store(1))
    tp.task_class(
        "T", params=("i",),
        space=lambda g: ((i,) for i in range(g.N)),
        flows=[ptg.FlowSpec(
            "X", ptg.RW,
            ins=[ptg.In(data=lambda g, i: (g.S, (0,)),
                        guard=lambda g, i: i == 0),
                 ptg.In(src=("T", lambda g, i: (i - 1,), "X"),
                        guard=lambda g, i: i > 0)],
            outs=[ptg.Out(dst=("T", lambda g, i: (i + 1,), "X"))])])
    return tp


@_fixture(rules=("dangling-output",))
def build_dangling_output() -> ptg.Taskpool:
    """A WRITE flow whose produced value nothing consumes (not tiled on
    a scratch collection) — silently dropped work."""
    tp = ptg.Taskpool("dangling", S=_store(1))
    tp.task_class(
        "T", params=("i",), space=lambda g: ((0,),),
        flows=[
            ptg.FlowSpec(
                "X", ptg.RW,
                ins=[ptg.In(data=lambda g, i: (g.S, (0,)))],
                outs=[ptg.Out(data=lambda g, i: (g.S, (0,)))]),
            ptg.FlowSpec("Y", ptg.WRITE, outs=[]),
        ])
    return tp


@_fixture(rules=("waw-hazard",))
def build_serving_quarantine() -> ptg.Taskpool:
    """Serving fixture: the taskpool shape a misbehaving tenant submits
    — two decode "requests" whose steps both write the SAME shared KV
    tile with no ordering edge (a WAW hazard; a correct tenant keys KV
    tiles per request). This is exactly what the registration-time lint
    gate (``analysis.lint=error``) refuses, quarantining the tenant
    before any of its tasks run; the CLI self-check additionally
    renders this report via ``LintReport.to_dot()`` — the quarantine
    evidence an operator gets for a refused tenant."""
    kv = _store(2)
    tp = ptg.Taskpool("tenant_decode", KV=kv)
    for req in ("DEC_A", "DEC_B"):
        tp.task_class(
            req, params=("t",), space=lambda g: ((t,) for t in range(2)),
            flows=[ptg.FlowSpec(
                "K", ptg.RW,
                ins=[ptg.In(data=lambda g, t: (g.KV, (0,)),
                            guard=lambda g, t: t == 0),
                     ptg.In(src=(req, lambda g, t: (t - 1,), "K"),
                            guard=lambda g, t: t > 0)],
                outs=[ptg.Out(dst=(req, lambda g, t: (t + 1,), "K"),
                              guard=lambda g, t: t < 1),
                      ptg.Out(data=lambda g, t: (g.KV, (0,)),
                              guard=lambda g, t: t == 1)])])
    return tp


# --------------------------------------------------------------------------
# Dynamic native-engine fixture (ISSUE 14): a seeded WAW on a DTD pool.
# DTD's declared-arg dataflow chains every writer of a tile, so the bug
# is seeded one level up — TWO collections registered under ONE label
# ("KV") alias the same logical tile (the classic user bug: two views
# of one buffer). The runtime's per-collection writer tracking cannot
# order them; dfsan's label-keyed tile state must flag the WAW — on the
# Python engine live, and on the native engine via the fold-time
# ring/manifest replay, with IDENTICAL class/flow/coords.
# --------------------------------------------------------------------------

_WAW_GATE = None


def _waw_w1(x):
    # parked until BOTH writers are inserted: on the Python engine the
    # live sanitizer's insert-time snapshot read would otherwise
    # observe w1's committed write and (bogusly, but by the documented
    # same-tile sync rule) order w2 after it on a fast machine —
    # determinism of the fixture must not hang on a scheduling race
    if _WAW_GATE is not None:
        _WAW_GATE.wait(10.0)
    return x + 1.0


def _waw_w2(x):
    return x + 10.0


def run_racy_dtd(native: int) -> list:
    """Run the seeded-WAW DTD fixture on the requested engine; returns
    the normalized race rows ``(kind, tile, {task, other})`` — task/
    other unordered because commit order between unordered writers is
    schedule-dependent by definition."""
    import threading

    import parsec_tpu as parsec
    from ..dsl import dtd
    from ..utils import mca_param
    global _WAW_GATE
    mca_param.set("pins", "dfsan")
    mca_param.set("runtime.native_dtd", native)
    try:
        ctx = parsec.init(nb_cores=2)
        ctx.start()
        kv1 = LocalCollection("KV", {(0,): 0.0})
        kv2 = LocalCollection("KV", {(0,): 0.0})
        tp = dtd.Taskpool("racy_dtd")
        ctx.add_taskpool(tp)
        _WAW_GATE = threading.Event()
        tp.insert_task(_waw_w1, dtd.TileArg(kv1, (0,), dtd.INOUT))
        tp.insert_task(_waw_w2, dtd.TileArg(kv2, (0,), dtd.INOUT))
        _WAW_GATE.set()
        tp.flush()
        tp.wait()
        engaged = tp._native is not None
        if engaged != bool(native):
            raise AssertionError(
                f"native={native} but engine engaged={engaged} — the "
                f"fixture must exercise the engine it names")
        rows = sorted(
            (r.kind, r.tile, tuple(sorted((r.task, r.other))))
            for r in ctx.dfsan.races)
        parsec.fini(ctx)
        return rows
    finally:
        mca_param.unset("pins")
        mca_param.unset("runtime.native_dtd")


def native_self_check() -> Tuple[int, list]:
    """The ISSUE 14 engine-parity check: the seeded DTD WAW must be
    reported by ring-fed dfsan on the NATIVE engine exactly as the
    live sanitizer reports it on the Python engine (same kind, same
    tile coords, same task labels). Returns (failures, log_lines);
    skips cleanly (0 failures) when the native toolchain is absent."""
    from .. import _native
    lines = []
    if not _native.available():
        lines.append(f"skip racy_dtd_native: native core unavailable "
                     f"({_native.build_error()})")
        return 0, lines
    py_rows = run_racy_dtd(0)
    nat_rows = run_racy_dtd(1)
    failures = 0
    if not any(k == "waw" for k, _t, _p in py_rows):
        failures += 1
        lines.append(f"FAIL racy_dtd python-engine: no WAW reported "
                     f"({py_rows})")
    if not any(k == "waw" and "KV(0,)" in t and
               any("_waw_w1(" in x for x in pair) and
               any("_waw_w2(" in x for x in pair)
               for k, t, pair in nat_rows):
        failures += 1
        lines.append(f"FAIL racy_dtd native-engine: WAW missing or "
                     f"lacking class/coords ({nat_rows})")
    if py_rows != nat_rows:
        failures += 1
        lines.append(f"FAIL racy_dtd: engine reports differ:\n"
                     f"  python: {py_rows}\n  native: {nat_rows}")
    if not failures:
        lines.append(f"ok   racy_dtd: WAW on KV(0,) reported "
                     f"identically by both engines ({nat_rows[0]})")
    return failures, lines


def self_check() -> Tuple[int, list]:
    """Lint every fixture and verify the expected rules fire with
    messages naming the task class, flow and coordinates; verify the
    clean control yields zero findings.  Returns (failures, log_lines).
    """
    from .lint import lint_taskpool
    failures = 0
    lines = []
    for name, (builder, rules) in sorted(FIXTURES.items()):
        tp = builder()
        report = lint_taskpool(tp)
        got = {f.rule for f in report.findings}
        if not rules:
            if report.findings:
                failures += 1
                lines.append(f"FAIL {name}: expected clean, got {got}")
            else:
                lines.append(f"ok   {name}: clean")
            continue
        missing = set(rules) - got
        if missing:
            failures += 1
            lines.append(f"FAIL {name}: rules {missing} not reported "
                         f"(got {got or 'nothing'})")
            continue
        # actionable messages: every expected finding names the task
        # class and flow, and instance-level findings carry coordinates
        # (structural per-class findings like CTL-with-data apply to the
        # whole class, so class.flow is the precise site)
        vague = [f for f in report.findings
                 if f.rule in rules and not (
                     f.task and (f.flow or "(" in f.message))]
        if vague:
            failures += 1
            lines.append(f"FAIL {name}: finding lacks task coordinates: "
                         f"{vague[0]}")
            continue
        shown = next(f for f in report.findings if f.rule in rules)
        lines.append(f"ok   {name}: {shown}")
        if name == "serving_quarantine":
            # the quarantined-tenant DAG must RENDER: the operator-facing
            # evidence for a lint-refused tenant is the DOT report with
            # the hazard edge marked
            dot = report.to_dot()
            if not (dot.lstrip().startswith("digraph")
                    and "waw-hazard" in dot):
                failures += 1
                lines.append(f"FAIL {name}: to_dot() did not render the "
                             "hazard DAG")
            else:
                lines.append(f"ok   {name}: to_dot() renders "
                             f"({len(dot)} bytes, hazard edge marked)")
    nfail, nlines = hot_config_self_check()
    failures += nfail
    lines += nlines
    return failures, lines


#: seeded hot-path config-read source — the shape PR 15 actually fixed
#: in wfq select(): a full registry get once per selected task
HOT_CONFIG_FIXTURE = '''\
class BadScheduler:
    def select(self, es):
        interleave = int(mca_param.get("serving.kv_prefill_interleave", 4))
        return self.pick(interleave)

    def _drain(self):
        while self.live():
            batch = int(mca_param.get("runtime.release_batch", 8))
            self.flush(batch)

    def install(self, context):
        # preamble read outside any hot function or loop: allowed
        self.quantum = int(mca_param.get("sched.quantum_us", 50))
'''


def hot_config_self_check() -> Tuple[int, list]:
    """The hot-config-read rule's own contract: the seeded fixture
    source MUST trip it (both the select() shape and the loop-body
    shape), and the SHIPPED sched/worker tree must be clean."""
    from .lint import _scan_hot_config_source, lint_hot_config
    failures = 0
    lines = []
    found = [f for f in _scan_hot_config_source(HOT_CONFIG_FIXTURE,
                                                "fixture.py")
             if f.severity == "error"]
    sites = {f.task.split(" ")[0] for f in found}
    if {"select", "_drain"} <= sites and len(found) == 2:
        lines.append(f"ok   hot_config_fixture: {found[0]}")
    else:
        failures += 1
        lines.append(f"FAIL hot_config_fixture: expected select+_drain "
                     f"flagged (and install clean), got {sites}")
    shipped = [f for f in lint_hot_config() if f.severity == "error"]
    if shipped:
        failures += 1
        lines.append(f"FAIL hot_config_shipped: sched/worker tree not "
                     f"clean: {shipped[0]}")
    else:
        lines.append("ok   hot_config_shipped: sched/* and worker loop "
                     "clean (cached_get / preamble reads only)")
    return failures, lines
