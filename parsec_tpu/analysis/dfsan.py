"""dfsan: runtime dataflow race sanitizer (a PINS module).

FastTrack-style vector-clock race detection (Flanagan & Freund, PLDI
2009) adapted to a task-dataflow runtime: the synchronizing objects are
*dependency releases*, not mutexes, and — unlike thread-based
FastTrack — clocks advance along dependency edges ONLY, never along a
worker thread's incidental program order.  Two DAG-unordered tasks stay
incomparable even when this run's schedule serialized them on one
worker, so a declared-dataflow hazard is flagged on EVERY run, not just
the unlucky interleavings.  Every task instance gets a vector clock
(stored in ``Task.vc``) built from

- the joined clocks of every predecessor that released a dep into it
  (``observe_edge`` — called from the release path in
  ``Context.complete_task`` for each :class:`SuccessorRef`),
- a fresh per-task epoch, and
- a global barrier base advanced at taskpool termination (termdet *is*
  a full synchronization point, so tile state survives across
  sequentially-run taskpools without false positives).

Collection-tile accesses observed through the runtime's release paths —
terminal ``DataRef`` write-backs in ``complete_task``, DTD's
``write_tile`` at retire, PTG ``data_lookup`` reads — are stamped with
the accessing task's clock and checked: a WRITE unordered with the
previous write (WAW) or with a recorded read (R→W), or a read unordered
with the last write (W→R), is a race.  DTD *insert-time* snapshot reads
are synchronization (the tile lock + retire protocol orders them — see
dsl/dtd.py); they join the tile's write clock into the inserted task
instead of being race-checked, which is also what keeps later writers
of a quiesced tile ordered WITHOUT a materialized dep edge.

Extras, per the PR-3 fast-path guard brief:

- **lock-order tracking**: the striped dependency-table locks
  (``_PendingDeps``) and DTD seq-stripe locks report acquisitions here
  (``wrap_lock``); held-while-acquiring edges build a lock-order graph
  and any cycle is flagged as an inversion.
- **determinism digest**: every tile keeps its *version sequence* (the
  ordered labels of its committed writers).  ``digest()`` hashes the
  per-tile sequences — schedule-independent iff the DAG fully orders
  each tile's writers, so two runs under different schedulers /
  ``runtime.release_batch`` / ``runtime.bypass_chain`` settings must
  produce bitwise-identical digests.
- **access-mode check**: at release, a body that returned a value for a
  READ/CTL flow (possible via dict returns) is flagged — the dynamic
  half of the lint's access-violation rule.

Install MCA-style (``pins = dfsan``) or explicitly::

    from parsec_tpu.analysis.dfsan import DataflowSanitizer
    san = DataflowSanitizer().install(ctx)
    ... run ...
    assert not san.races
    print(san.digest())

Overhead: every observed access takes one global sanitizer lock and
joins O(#threads) clock entries — runs measure 2-5x slowdown on
task-rate-bound workloads; it is a debugging/CI tool, not a production
default (the reference's PINS modules share this contract).
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..core.task import FlowAccess
from ..profiling.pins import PinsEvent
from ..profiling.pins_modules import PinsModule

VC = Dict[int, int]


def _leq(a: VC, b: VC) -> bool:
    """a happens-before-or-equals b (componentwise ≤)."""
    for k, v in a.items():
        if v > b.get(k, -1):
            return False
    return True


def _join(into: VC, other: Optional[VC]) -> VC:
    if other:
        for k, v in other.items():
            if v > into.get(k, -1):
                into[k] = v
    return into


@dataclass
class RaceReport:
    """One detected race / violation."""
    kind: str                  # "waw" | "war" | "raw" | "lock-order" |
    #                            "access-violation"
    tile: str = ""
    task: str = ""
    other: str = ""
    message: str = ""

    def __str__(self) -> str:
        return f"[{self.kind}] {self.message}"


Epoch = Tuple[int, int]                # (component, clock)


class _TileState:
    __slots__ = ("write_epoch", "write_vc", "write_task", "reads", "seq")

    def __init__(self) -> None:
        self.write_epoch: Optional[Epoch] = None
        self.write_vc: Optional[VC] = None     # writer's full knowledge
        self.write_task: str = ""
        self.reads: List[Tuple[Epoch, str]] = []
        self.seq: List[str] = []       # committed writer labels, in order


class _OrderedLock:
    """Context-manager shim around a real lock that reports acquisition
    order to the sanitizer (returned by :meth:`DataflowSanitizer.
    wrap_lock`; the runtime only constructs it while a sanitizer is
    installed, so the un-sanitized hot path stays a bare Lock)."""

    __slots__ = ("_lock", "_san", "_domain", "_stripe")

    def __init__(self, lock, san: "DataflowSanitizer", domain: str,
                 stripe: int):
        self._lock = lock
        self._san = san
        self._domain = domain
        self._stripe = stripe

    def __enter__(self):
        self._lock.acquire()
        self._san.lock_acquired(self._domain, self._stripe)
        return self

    def __exit__(self, *exc):
        self._san.lock_released(self._domain, self._stripe)
        self._lock.release()
        return False


class DataflowSanitizer(PinsModule):
    """The ``dfsan`` PINS module (see module docstring)."""

    name = "dfsan"

    def __init__(self) -> None:
        super().__init__()
        self._lock = threading.Lock()
        self._comp: Dict[int, int] = {}          # thread ident -> component
        self._ncomp = 0                          # next component id (live
        #                                          threads AND replayed tasks)
        self._thread_vc: Dict[int, VC] = {}
        self._pending: Dict[Any, VC] = {}        # task key -> joined pred VC
        self._tiles: Dict[Tuple[str, Tuple], _TileState] = {}
        self._base: VC = {}                      # barrier base (termdet)
        self._max: VC = {}                       # join of every task VC
        self.races: List[RaceReport] = []
        self._seen_race_keys: set = set()
        # lock-order graph: (domain, stripe) -> set of locks acquired
        # while this one was held
        self._lock_edges: Dict[Tuple[str, int], set] = {}
        self._held = threading.local()
        self.stats = {"reads": 0, "writes": 0, "edges": 0, "tasks": 0,
                      "repo_accesses": 0, "lock_acquires": 0,
                      "native_replayed_pools": 0,
                      "native_replay_skipped": 0,
                      "native_lock_pairs": 0}

    # ------------------------------------------------------------ lifecycle
    def install(self, context) -> "DataflowSanitizer":
        super().install(context)
        context.dfsan = self
        # native_ok=True (ISSUE 14): these per-task hooks only fire on
        # the Python engine, and natively-executed DTD pools are
        # covered EXACTLY by the fold-time ring replay
        # (replay_native_pool) — so the sanitizer itself no longer
        # disqualifies the native engine via needs_python_engine()
        self._sub(PinsEvent.TASKPOOL_INIT, self._taskpool_init,
                  native_ok=True)
        self._sub(PinsEvent.RELEASE_DEPS_BEGIN, self._release_begin,
                  native_ok=True)
        self._sub(PinsEvent.COMPLETE_EXEC_END, self._complete_end,
                  native_ok=True)
        # adopt taskpools registered before install
        with context._lock:
            pools = list(context._taskpools_by_name.values())
        for tp in pools:
            self._taskpool_init(tp)
        from ..core.datarepo import DataRepo
        DataRepo.observer = self._repo_access
        return self

    def uninstall(self) -> None:
        super().uninstall()
        from ..core.datarepo import DataRepo
        if DataRepo.observer is self._repo_access:
            DataRepo.observer = None
        if getattr(self.context, "dfsan", None) is self:
            self.context.dfsan = None
        with self.context._lock:
            pools = list(self.context._taskpools_by_name.values())
        for tp in pools:
            if getattr(tp.pending, "sanitizer", None) is self:
                tp.pending.sanitizer = None

    def _taskpool_init(self, tp) -> None:
        tp.pending.sanitizer = self      # striped-lock order reporting

    def reset(self) -> None:
        """Drop tile/race state (e.g. between digest comparison runs)."""
        with self._lock:
            self._tiles.clear()
            self._pending.clear()
            self.races.clear()
            self._seen_race_keys.clear()
            self._lock_edges.clear()

    # ------------------------------------------------------------- clocks
    def _alloc_comp(self) -> int:
        """Next clock component id (caller holds the sanitizer lock).
        Live worker threads get one component each; natively-REPLAYED
        tasks get one component PER TASK — with shared components an
        inherited later epoch would shadow an unordered earlier one
        (the per-thread approximation note below), and a fold-time
        replay on one thread would shadow everything."""
        c = self._ncomp
        self._ncomp += 1
        return c

    def _comp_of(self, tid: int) -> int:
        c = self._comp.get(tid)
        if c is None:
            c = self._comp[tid] = self._alloc_comp()
        return c

    def _clock_of_locked(self, task) -> Tuple[Epoch, VC]:
        """Task clock ``(epoch, vc)``, lazily initialized on first
        observation.  ``vc`` is the task's *inherited knowledge* —
        barrier base ⊔ joined predecessor releases; ``epoch`` is its own
        unique (component, clock) stamp, which enters OTHER tasks'
        clocks only through dependency-edge joins, never its own vc.

        Deliberately NOT joined with the executing thread's history
        (where classic thread-based FastTrack would): in a task-dataflow
        runtime the DAG is the program and the worker threads are
        incidental, so clocks advance along dependency edges only.  Two
        DAG-unordered tasks stay incomparable even when this run's
        schedule serialized them on one worker.  (Approximation note:
        components are per-thread for compactness, so an inherited
        LATER epoch on a component can shadow an unordered earlier one
        — a missed race is possible in that narrow pattern, a false
        race is not; the static lint is the exact check.)"""
        clk = task.vc
        if clk is not None:
            return clk
        tid = threading.get_ident()
        comp = self._comp_of(tid)
        tvc = self._thread_vc.setdefault(tid, {})
        tvc[comp] = tvc.get(comp, 0) + 1          # fresh epoch for the task
        epoch = (comp, tvc[comp])
        vc = dict(self._base)
        _join(vc, self._pending.pop(task.key, None))
        task.vc = clk = (epoch, vc)
        _join(self._max, vc)
        self._max[comp] = max(self._max.get(comp, 0), epoch[1])
        self.stats["tasks"] += 1
        return clk

    @staticmethod
    def _epoch_leq(e: Epoch, vc: VC) -> bool:
        """FastTrack's e ⊑ VC: has ``vc`` inherited epoch ``e``?"""
        return e[1] <= vc.get(e[0], 0)

    def barrier(self) -> None:
        """Full synchronization (taskpool termination): everything
        observed so far happens-before everything after (``_max`` holds
        the join of every issued epoch)."""
        with self._lock:
            _join(self._base, self._max)

    def base_snapshot(self) -> VC:
        """Copy of the current barrier base. The native driver takes
        one when an aborted pool enters the RETIRING state (still
        draining): its termination barrier advances ``_base`` before
        the pump folds the drained engine, and a replay seeded from
        the post-barrier base would retroactively order the pool's
        tasks after concurrent pools' accesses — excusing real
        races. ``replay_native_pool`` seeds from the snapshot when
        the engine carries one."""
        with self._lock:
            return dict(self._base)

    # ----------------------------------------------------------- HB edges
    def observe_edge(self, src_task, ref) -> None:
        """One dependency release src_task → ref (called by the release
        path BEFORE the dep is counted, so the successor's clock is
        ready before it can run)."""
        key = ref.task_class.make_key(ref.locals)
        with self._lock:
            epoch, vc = self._clock_of_locked(src_task)
            p = self._pending.setdefault(key, {})
            _join(p, vc)
            p[epoch[0]] = max(p.get(epoch[0], 0), epoch[1])
            self.stats["edges"] += 1

    def _complete_end(self, es, task) -> None:
        with self._lock:
            self._clock_of_locked(task)     # ensure every task is stamped

    # --------------------------------------------------------- tile access
    @staticmethod
    def _tile_key(dc, key) -> Tuple[str, Tuple]:
        # shared with the static lint so static findings and runtime
        # race reports / digests name tiles identically
        from .model import _tile_key
        return _tile_key(dc, key)

    def _race(self, kind: str, tile: str, task: str, other: str,
              message: str) -> None:
        rk = (kind, tile, task, other)
        if rk in self._seen_race_keys:
            return
        self._seen_race_keys.add(rk)
        self.races.append(RaceReport(kind=kind, tile=tile, task=task,
                                     other=other, message=message))

    def _write_locked(self, epoch: Epoch, vc: VC, label: str, tk) -> None:
        """Stamp one committed write (caller holds the sanitizer lock):
        the ONE copy of the WAW/RAW checks + tile-state update, shared
        by the live ``observe_write`` path and the native-pool replay
        so reports and digests cannot drift between engines."""
        st = self._tiles.setdefault(tk, _TileState())
        tile_s = f"{tk[0]}{tk[1]}"
        if st.write_epoch is not None and \
                not self._epoch_leq(st.write_epoch, vc):
            self._race("waw", tile_s, label, st.write_task,
                       f"unordered writes to {tile_s}: {label} vs "
                       f"{st.write_task} — final version is "
                       f"schedule-dependent")
        for repoch, rlabel in st.reads:
            if rlabel != label and not self._epoch_leq(repoch, vc):
                self._race("raw", tile_s, label, rlabel,
                           f"write to {tile_s} by {label} unordered "
                           f"with read by {rlabel}")
        st.write_epoch = epoch
        st.write_vc = dict(vc)
        st.write_task = label
        st.reads.clear()
        st.seq.append(label)
        self.stats["writes"] += 1

    def observe_write(self, task, dc, key) -> None:
        """A committed tile write (DataRef write-back / DTD retire)."""
        tk = self._tile_key(dc, key)
        label = repr(task)
        with self._lock:
            epoch, vc = self._clock_of_locked(task)
            self._write_locked(epoch, vc, label, tk)
        if self.context is not None:
            self.context.pins.data_write(task, dc, key)

    def observe_read(self, task, dc, key, sync: bool = False) -> None:
        """A tile read. ``sync=True`` (DTD insert-time snapshots, which
        the tile-lock/retire protocol already orders) joins the tile's
        write clock into the reader instead of race-checking."""
        tk = self._tile_key(dc, key)
        with self._lock:
            st = self._tiles.setdefault(tk, _TileState())
            if sync:
                if st.write_epoch is not None and task is not None:
                    p = self._pending.setdefault(task.key, {})
                    _join(p, st.write_vc)
                    c, k = st.write_epoch
                    p[c] = max(p.get(c, 0), k)
                self.stats["reads"] += 1
            else:
                epoch, vc = self._clock_of_locked(task)
                label = repr(task)
                tile_s = f"{tk[0]}{tk[1]}"
                if st.write_epoch is not None and \
                        st.write_task != label and \
                        not self._epoch_leq(st.write_epoch, vc):
                    self._race("war", tile_s, label, st.write_task,
                               f"read of {tile_s} by {label} unordered "
                               f"with write by {st.write_task} — may "
                               f"observe either version")
                st.reads.append((epoch, label))
                if len(st.reads) > 512:
                    st.reads = st.reads[-256:]
                self.stats["reads"] += 1
        if self.context is not None:
            self.context.pins.data_read(task, dc, key)

    def _repo_access(self, op: str, repo, key, flow_index: int) -> None:
        """DataRepo entry fill/take observer (datarepo release path)."""
        self.stats["repo_accesses"] += 1

    # ------------------------------------------------------- access modes
    def _release_begin(self, es, task) -> None:
        tc = task.task_class
        for name in task.output:
            flow = tc.flow_by_name.get(name)
            if flow is None:
                continue
            if flow.is_ctl or not (flow.access & FlowAccess.WRITE):
                with self._lock:    # _race mutates shared race state
                    self._race(
                        "access-violation", "", repr(task), name,
                        f"{task!r}: body returned a value for flow "
                        f"{name!r} declared {FlowAccess(flow.access).name}"
                        f" — only WRITE/RW flows are output flows "
                        f"(core.task)")

    # --------------------------------------------------------- lock order
    def wrap_lock(self, lock, domain: str, stripe: int) -> _OrderedLock:
        return _OrderedLock(lock, self, domain, stripe)

    def lock_acquired(self, domain: str, stripe: int) -> None:
        key = (domain, stripe)
        held = getattr(self._held, "stack", None)
        if held is None:
            held = self._held.stack = []
        self.stats["lock_acquires"] += 1
        if held:
            with self._lock:
                for h in held:
                    if h == key:
                        continue
                    self._lock_edges.setdefault(h, set()).add(key)
                    if self._lock_path(key, h):
                        self._race(
                            "lock-order", "", f"{domain}[{stripe}]",
                            f"{h[0]}[{h[1]}]",
                            f"lock-order inversion: {h[0]}[{h[1]}] held "
                            f"while acquiring {domain}[{stripe}], but the "
                            f"reverse order was also observed")
        held.append(key)

    def lock_released(self, domain: str, stripe: int) -> None:
        held = getattr(self._held, "stack", None)
        if held and (domain, stripe) in held:
            held.remove((domain, stripe))

    def _lock_path(self, src, dst) -> bool:
        """Is there an order-graph path src → dst? (caller holds lock)"""
        stack, seen = [src], set()
        while stack:
            u = stack.pop()
            if u == dst:
                return True
            if u in seen:
                continue
            seen.add(u)
            stack.extend(self._lock_edges.get(u, ()))
        return False

    def feed_native_lock_pairs(self, pairs: int) -> None:
        """Fold the C lock-discipline recorder's acquisition-pair
        bitmask (``pdtd_stats`` ``lock_pairs``, bit ``held*5+acquired``
        over ``_native.PDTD_LOCK_DOMAINS``) into the inversion
        detector. The pdtd hot loop's discipline is nesting-free, so a
        healthy engine contributes NOTHING here; any pair lands in the
        shared order graph (domains prefixed ``native-``), and a
        same-domain pair — two nested entry locks, the classic DTD
        deadlock shape — is an inversion by itself."""
        if not pairs:
            return
        from .. import _native
        doms = _native.PDTD_LOCK_DOMAINS
        n = len(doms)
        with self._lock:
            for held in range(n):
                for acq in range(n):
                    if not (pairs >> (held * n + acq)) & 1:
                        continue
                    self.stats["native_lock_pairs"] += 1
                    hk = (f"native-{doms[held]}", 0)
                    ak = (f"native-{doms[acq]}", 0)
                    if hk == ak:
                        self._race(
                            "lock-order", "", f"{ak[0]}[0]",
                            f"{hk[0]}[0]",
                            f"lock-order inversion: nested same-domain "
                            f"native pdtd locks ({doms[held]}) — the "
                            f"self-deadlock shape")
                        continue
                    self._lock_edges.setdefault(hk, set()).add(ak)
                    if self._lock_path(ak, hk):
                        self._race(
                            "lock-order", "", f"{ak[0]}[0]",
                            f"{hk[0]}[0]",
                            f"lock-order inversion: {hk[0]}[0] held "
                            f"while acquiring {ak[0]}[0], but the "
                            f"reverse order was also observed")

    # ---------------------------------------------------- native replay
    def replay_native_pool(self, engine) -> None:
        """Fold-time replay of a natively-executed DTD pool (ISSUE 14).

        The native engine runs insert→release entirely behind the C
        ABI, so the live per-access hooks never fire; instead it hands
        this method (from ``NativeDTD.obs_retire``, BEFORE the
        termination barrier advances ``_base``):

        - **insert-time access manifests** — per tile-bearing task, in
          program order: sync snapshot reads (the tile-lock/retire
          protocol orders them — replayed as clock joins, exactly the
          live ``observe_read(sync=True)``), linked-predecessor HB
          edges (the ``linked_out``-resolved goal edges — the same
          edges ``observe_edge`` sees live, and the superset of the
          ring records' ``parent_seq``), and declared writes;
        - **commit evidence** — which declared writes the body actually
          produced (``observe_write`` stamps only produced flows), plus
          dynamic access-mode violations captured at normalize time;
        - **the frozen event rings** — the completion ground truth: on
          a clean pool every inserted task completed (termination
          requires drain), on an ABORTED pool only ring-recorded seqs
          are replayed, and if the rings wrapped (records dropped) the
          replay is SKIPPED and counted, never guessed — a missing
          happens-before source would fabricate races;
        - **the C lock-discipline pair table** (``lock_pairs``), folded
          into the inversion detector either way.

        Tasks replay in seq order (= insertion program order, a
        topological order of the pool DAG — predecessor ids are always
        smaller). Each replayed task gets its OWN clock component, so
        the exactness matches live operation's per-thread components or
        better; labels are ``class(seq)``, identical to the Python
        engine's ``Task.__repr__``, which keeps race reports AND the
        per-tile version digests bitwise-comparable across engines."""
        manifests = getattr(engine, "_dfsan_manifest", None)
        if manifests is None:
            return
        stats = engine.stats()
        self.feed_native_lock_pairs(stats.get("lock_pairs", 0))
        # the C recorder's acquisition count folds into the same row
        # the Python _OrderedLock wrapper feeds — ONE "how much lock
        # traffic did the sanitizer actually see" surface per run
        self.stats["lock_acquires"] += stats.get("lock_acquires", 0)
        tp = engine.tp
        if tp.error is None:
            replay = sorted(manifests)
        elif stats.get("obs_dropped", 0) or not getattr(engine, "_obs",
                                                        False):
            # an aborted pool replays only ring-EVIDENCED completions;
            # wrapped rings — or rings that never enabled (allocation
            # failure) — mean the evidence is gone: skip LOUDLY, never
            # report a fabricated clean replay
            with self._lock:
                self.stats["native_replay_skipped"] += 1
            return
        else:
            done_seqs: set = set()
            for arr in engine.obs_drain():
                done_seqs.update(int(s) for s in arr["seq"])
            replay = sorted(s for s in manifests if s in done_seqs)
        commits = getattr(engine, "_dfsan_commits", {})
        names = engine.class_names
        completed = stats.get("completed_native", 0) + \
            stats.get("completed_python", 0)
        fired: List[Tuple[str, Any, Any]] = []
        with self._lock:
            # retiring-path folds run AFTER the pool's own termination
            # barrier — seed task clocks from the base snapshot taken
            # at termination (base_snapshot), not the advanced _base
            base = getattr(engine, "_dfsan_base", None)
            if base is None:
                base = self._base
            clocks: Dict[int, Tuple[Epoch, VC]] = {}
            # last replayed committed write PER RUNTIME TILE (collection
            # object identity + key): a sync snapshot read joins THIS,
            # not the label-keyed tile state — the tile-lock/retire
            # protocol only orders accesses through the same collection
            # tile, so label-aliased collections (two views of one
            # buffer, the seeded-WAW fixture) must NOT be retroactively
            # ordered by the replay. Writes from pools that already
            # terminated are covered by the barrier base. (Known
            # approximation, stricter than live: a CONCURRENT pool's
            # commit that a live insert-time read would have observed
            # is not joined — same-label cross-pool traffic without an
            # intervening termination is flagged, not excused.)
            rt_last: Dict[Tuple[int, Tuple], Tuple[Epoch, VC]] = {}
            for seq in replay:
                cls_id, accesses = manifests[seq]
                label = f"{names[cls_id]}({seq})"
                vc = dict(base)
                committed = commits.get(seq, ())
                writes = []
                for acc in accesses:
                    op = acc[0]
                    if op == "edge":
                        pc = clocks.get(acc[1])
                        if pc is not None:
                            pep, pvc = pc
                            _join(vc, pvc)
                            if pep[1] > vc.get(pep[0], -1):
                                vc[pep[0]] = pep[1]
                        self.stats["edges"] += 1
                    elif op == "sync":
                        tk = self._tile_key(acc[1], acc[2])
                        self._tiles.setdefault(tk, _TileState())
                        last = rt_last.get((id(acc[1]), tk[1]))
                        if last is not None:
                            pep, pvc = last
                            _join(vc, pvc)
                            if pep[1] > vc.get(pep[0], -1):
                                vc[pep[0]] = pep[1]
                        self.stats["reads"] += 1
                        fired.append(("r", acc[1], acc[2]))
                    elif acc[3] in committed:   # "write", produced
                        writes.append(acc)
                comp = self._alloc_comp()
                epoch = (comp, 1)
                clocks[seq] = (epoch, vc)
                for acc in writes:
                    tk = self._tile_key(acc[1], acc[2])
                    self._write_locked(epoch, vc, label, tk)
                    rt_last[(id(acc[1]), tk[1])] = (epoch, vc)
                    fired.append(("w", acc[1], acc[2]))
                _join(self._max, vc)
                if 1 > self._max.get(comp, -1):
                    self._max[comp] = 1
            for (seq, cls_name, fname, access) in \
                    getattr(engine, "_dfsan_violations", ()):
                self._race(
                    "access-violation", "", f"{cls_name}({seq})", fname,
                    f"{cls_name}({seq}): body returned a value for "
                    f"flow {fname!r} declared "
                    f"{FlowAccess(access).name} — only WRITE/RW flows "
                    f"are output flows (core.task)")
            self.stats["tasks"] += completed
            self.stats["native_replayed_pools"] += 1
        if self.context is not None:
            pins = self.context.pins
            for kind, dc, key in fired:
                # same PINS rebroadcast as the live paths; the replay
                # has no Task object, so observers receive task=None
                if kind == "w":
                    pins.data_write(None, dc, key)
                else:
                    pins.data_read(None, dc, key)

    # ------------------------------------------------------------- digest
    def digest(self) -> str:
        """Per-tile version-sequence digest: sha256 over the committed
        writer sequences, keyed by tile.  Schedule-independent iff the
        DAG fully orders every tile's writers — the regression handle
        for scheduler / release-path optimizations."""
        h = hashlib.sha256()
        with self._lock:
            for tk in sorted(self._tiles, key=repr):
                st = self._tiles[tk]
                h.update(repr((tk, tuple(st.seq))).encode())
        return h.hexdigest()

    def version_sequences(self) -> Dict[Tuple[str, Tuple], List[str]]:
        with self._lock:
            return {tk: list(st.seq) for tk, st in self._tiles.items()}

    # ------------------------------------------------------------- report
    def report(self) -> Dict[str, Any]:
        return {"races": [str(r) for r in self.races],
                "digest": self.digest(), **self.stats}


def get(context) -> Optional[DataflowSanitizer]:
    """The installed sanitizer of ``context`` (None when off)."""
    return getattr(context, "dfsan", None)
