"""Symbolic dataflow model of a PTG taskpool.

The lint (analysis/lint.py) needs a *materialized* view of what the
closed-form flow declarations actually generate: every task instance of
every class over its bounded parameter space (``enumerate_space()``),
every producer→consumer edge, and every collection-tile access.  The
reference audits the same information at two places — the JDF compiler's
``jdf_sanity_checks`` (jdf.c) statically and the iterators_checker PINS
module at runtime; this model is the shared substrate for both kinds of
check here.

The model never runs task bodies: producer-side expansion walks the
``FlowSpec.outs`` declarations directly (the same closures
``PTGTaskClass._iterate_successors`` evaluates), so building it is pure
and side-effect free.  Spaces are bounded by construction in PTG;
``max_tasks`` caps the enumeration so a registration-time lint on a huge
taskpool degrades to the structural (per-class) checks instead of
scanning millions of instances.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from ..core.task import FlowAccess


def _tile_key(dc, key) -> Tuple[str, Tuple]:
    """Stable identity of a collection tile: (collection label, key).
    Shared by the static lint AND the dfsan runtime sanitizer so both
    name tiles identically in findings and digests."""
    label = getattr(dc, "name", None)
    if not label:
        label = f"dc{getattr(dc, 'dc_id', id(dc))}"
    return (label, tuple(key) if isinstance(key, (tuple, list)) else (key,))


def _norm(coords) -> Tuple:
    """Normalize a dep-target coordinate to a tuple (bare scalar = one
    coordinate, matching the Out-dst convention)."""
    return tuple(coords) if isinstance(coords, (tuple, list)) else (coords,)


@dataclass
class TileAccess:
    """One declared collection access of a task instance."""
    node: int                 # index into Model.nodes
    flow: str
    tile: Tuple[str, Tuple]
    access: FlowAccess
    kind: str                 # "read" (In.data) | "write" (Out.data)


@dataclass
class Edge:
    """One producer→consumer dependency edge between task instances."""
    src: int
    dst: int
    src_flow: str
    dst_flow: str


class Node:
    """A task instance (class name + parameter assignment)."""

    __slots__ = ("idx", "tc", "coords")

    def __init__(self, idx: int, tc, coords: Tuple[int, ...]):
        self.idx = idx
        self.tc = tc
        self.coords = coords

    @property
    def label(self) -> str:
        return f"{self.tc.name}({', '.join(map(str, self.coords))})"

    def __repr__(self) -> str:
        return self.label


def _is_lintable_class(tc) -> bool:
    """PTG-style classes expose closed-form specs + a bounded space; DTD
    wire classes and hand-built TaskClass vtables do not."""
    return hasattr(tc, "spec_list") and hasattr(tc, "enumerate_space")


@dataclass
class Model:
    """Materialized instance DAG of a (PTG) taskpool."""

    taskpool: Any
    nodes: List[Node] = field(default_factory=list)
    index: Dict[Tuple[str, Tuple], int] = field(default_factory=dict)
    succ: List[List[int]] = field(default_factory=list)
    edges: List[Edge] = field(default_factory=list)
    # (src_idx, src_flow, dst_idx, dst_flow) actually emitted by outs —
    # the consumer-side (ins) checks cross-validate against this
    produced: Set[Tuple[int, str, int, str]] = field(default_factory=set)
    reads: Dict[Tuple[str, Tuple], List[TileAccess]] = field(default_factory=dict)
    writes: Dict[Tuple[str, Tuple], List[TileAccess]] = field(default_factory=dict)
    # per-node terminal writes / touched tiles / affinity target
    # (owner-computes check)
    node_writes: Dict[int, List[Tuple[str, Tuple]]] = field(default_factory=dict)
    node_touch: Dict[int, set] = field(default_factory=dict)
    node_affinity: Dict[int, Tuple[str, Tuple]] = field(default_factory=dict)
    # tile label -> live collection object (data/recovery.py resolves
    # lost-tile ownership and cut-read sources through this)
    collections: Dict[str, Any] = field(default_factory=dict)
    # build diagnostics consumed by the lint
    problems: List[Tuple[str, str, str, str]] = field(default_factory=list)
    #         (rule, task_label, flow, message)
    skipped_classes: List[str] = field(default_factory=list)
    truncated: bool = False

    # -- ordering -----------------------------------------------------------
    def topo_order(self) -> Tuple[List[int], List[int]]:
        """Kahn's algorithm: (topological order, nodes left on a cycle)."""
        indeg = [0] * len(self.nodes)
        for outs in self.succ:
            for d in outs:
                indeg[d] += 1
        stack = [i for i, d in enumerate(indeg) if d == 0]
        order: List[int] = []
        while stack:
            u = stack.pop()
            order.append(u)
            for d in self.succ[u]:
                indeg[d] -= 1
                if indeg[d] == 0:
                    stack.append(d)
        on_cycle = [i for i, d in enumerate(indeg) if d > 0]
        return order, on_cycle

    def find_cycle(self) -> Optional[List[int]]:
        """One concrete dependency cycle (node indices, first == last),
        or None when the instance DAG is acyclic."""
        _, on_cycle = self.topo_order()
        if not on_cycle:
            return None
        # Kahn leftovers include nodes merely DOWNSTREAM of a cycle;
        # iteratively trim members without an in-set successor until
        # every survivor provably has one (the cycles themselves), so
        # the walk below can never dead-end
        members = set(on_cycle)
        while True:
            drop = [u for u in members
                    if not any(d in members for d in self.succ[u])]
            if not drop:
                break
            members.difference_update(drop)
        start = min(members)
        path = [start]
        seen_at = {start: 0}
        u = start
        while True:
            u = next(d for d in self.succ[u] if d in members)
            if u in seen_at:
                return path[seen_at[u]:] + [u]
            seen_at[u] = len(path)
            path.append(u)

    def reaches(self, src: int, dst: int) -> bool:
        """Is there a dependency path src ⇝ dst? (iterative DFS with a
        per-source descendant memo — hazard queries cluster by source)."""
        if src == dst:
            return True
        memo = self.__dict__.setdefault("_desc_memo", {})
        desc = memo.get(src)
        if desc is None:
            desc = set()
            stack = list(self.succ[src])
            while stack:
                u = stack.pop()
                if u in desc:
                    continue
                desc.add(u)
                # splice in an already-computed memo instead of re-walking
                sub = memo.get(u)
                if sub is not None:
                    desc |= sub
                    continue
                stack.extend(self.succ[u])
            memo[src] = desc
        return dst in desc

    def ordered(self, a: int, b: int) -> bool:
        return self.reaches(a, b) or self.reaches(b, a)


def build_model(tp, max_tasks: int = 0) -> Model:
    """Materialize the instance DAG of ``tp``.

    ``max_tasks`` (0 = the ``analysis.lint_max_tasks`` MCA default)
    bounds the enumeration; past the cap the model is marked
    ``truncated`` and instance-level checks are skipped by the lint.
    """
    from ..utils import mca_param
    if max_tasks <= 0:
        max_tasks = int(mca_param.get("analysis.lint_max_tasks", 20000))

    m = Model(taskpool=tp)
    g = getattr(tp, "g", None)
    classes = [tc for tc in tp.task_classes if _is_lintable_class(tc)]
    m.skipped_classes = [tc.name for tc in tp.task_classes
                         if not _is_lintable_class(tc)]
    if g is None or not classes:
        m.truncated = bool(tp.task_classes)
        return m

    # pass 1: enumerate every instance
    total = 0
    for tc in classes:
        for p in tc.enumerate_space():
            total += 1
            if total > max_tasks:
                m.truncated = True
                return m
            idx = len(m.nodes)
            node = Node(idx, tc, tuple(p))
            m.nodes.append(node)
            m.succ.append([])
            m.index[(tc.name, tuple(p))] = idx

    def _reg_tile(dc, key):
        tk = _tile_key(dc, key)
        m.collections.setdefault(tk[0], dc)
        return tk

    # pass 2: producer-side expansion (outs) — edges + collection writes
    for node in m.nodes:
        tc, p = node.tc, node.coords
        for spec in tc.spec_list:
            for dep in spec.outs:
                if not dep.active(g, p):
                    continue
                if dep.data is not None:
                    dc, key = dep.data(g, *p)
                    tk = _reg_tile(dc, key)
                    acc = TileAccess(node.idx, spec.name, tk, spec.access,
                                     "write")
                    m.writes.setdefault(tk, []).append(acc)
                    m.node_writes.setdefault(node.idx, []).append(tk)
                    continue
                cls_name, params_fn, dst_flow = dep.dst
                dst_tc = tp._tc_by_name.get(cls_name)
                if dst_tc is None:
                    m.problems.append((
                        "phantom-target", node.label, spec.name,
                        f"{node.label}.{spec.name} -> {cls_name}.{dst_flow}: "
                        f"no task class named {cls_name!r} in the taskpool"))
                    continue
                targets = params_fn(g, *p)
                if isinstance(targets, tuple):
                    targets = [targets]
                for tgt in targets:
                    tgt = _norm(tgt)
                    dst_idx = m.index.get((cls_name, tgt))
                    if dst_idx is None:
                        coords = ", ".join(map(str, tgt))
                        m.problems.append((
                            "phantom-target", node.label, spec.name,
                            f"{node.label}.{spec.name} -> "
                            f"{cls_name}({coords}).{dst_flow}: target task "
                            f"instance does not exist in the class space"))
                        continue
                    m.succ[node.idx].append(dst_idx)
                    m.edges.append(Edge(node.idx, dst_idx, spec.name,
                                        dst_flow))
                    m.produced.add((node.idx, spec.name, dst_idx, dst_flow))

    # pass 3: consumer-side (ins) — collection reads; the lint resolves
    # the In.src expectations against m.produced
    for node in m.nodes:
        tc, p = node.tc, node.coords
        for spec in tc.spec_list:
            try:
                dep = tc._active_in(g, spec, p)
            except RuntimeError as exc:
                m.problems.append((
                    "ambiguous-guards", node.label, spec.name, str(exc)))
                continue
            if dep is None or dep.data is None:
                continue
            dc, key = dep.data(g, *p)
            tk = _reg_tile(dc, key)
            acc = TileAccess(node.idx, spec.name, tk, spec.access, "read")
            m.reads.setdefault(tk, []).append(acc)

    # pass 4: affinity targets + touched tiles (owner-computes check).
    # "Touched" = any tile a flow declares it works on (FlowSpec.tile),
    # plus collection reads/writes — a task placed on ANY of those is
    # owner-computes-reasonable (e.g. geqrf's TSMQR sits on its trailing
    # A2 tile while its C1 pipeline hand-off writes the row tile).
    for node in m.nodes:
        touch = m.node_touch.setdefault(node.idx, set())
        touch.update(m.node_writes.get(node.idx, ()))
        for spec in node.tc.spec_list:
            if spec.tile is not None:
                dc, key = spec.tile(g, *node.coords)
                touch.add(_reg_tile(dc, key))
        aff = getattr(node.tc, "affinity", None)
        if aff is None:
            continue
        dc, key = aff(g, *node.coords)
        m.node_affinity[node.idx] = _reg_tile(dc, key)
    for tk, accs in m.reads.items():
        for a in accs:
            m.node_touch.setdefault(a.node, set()).add(tk)

    return m
