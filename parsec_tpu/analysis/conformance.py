"""Trace-refinement check: replay recorded runtime events through the
protocol models and report the FIRST non-refining step.

protocheck proves properties of the *models*; this pass keeps the
models honest against the *implementation*.  The PR 13 observability
plane already records the ground truth — ``kvpage`` events from
:class:`~parsec_tpu.serving.kv.KVPagePool` and ``admission``
admit/retire/reconcile events from the serving runtime, in the Python
rings and the native engine rings alike — so refinement is a pure
replay over ``Trace.to_records()`` output: feed each event to the
matching protocol's transition rules and stop at the first event the
model's guards cannot explain (index, event, reason).  A clean replay
certifies the traced run is a behavior of the checked model; the
upcoming native wfq/admission port inherits this as its refinement
oracle.

Event vocabulary replayed here:

- ``kvpage`` — phase alloc/retain/release/free/cow/write, object = pid,
  info.refs = refcount after the op (cross-checked against the replay's
  own bookkeeping, so a *missing* event is caught as a refs mismatch);
- ``admission`` — phase admit/retire/reconcile, info.tenant/rows/
  inflight (depth after), window/soft on admits.  The begin/end park
  spans PR 13 records are latency annotations, not protocol steps, and
  are skipped.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from .lint import ERROR

Record = Dict[str, Any]


@dataclass
class Mismatch:
    """One non-refining step: the event the model cannot explain."""
    index: int                    # position in the replayed stream
    event: Record
    reason: str

    def __str__(self) -> str:
        ev = self.event
        return (f"[{ERROR}] non-refining step at #{self.index}: "
                f"{ev.get('key')}/{ev.get('phase')} "
                f"object={ev.get('object')!r} — {self.reason}")


@dataclass
class ConformanceReport:
    """Replay verdict for one protocol over one event stream."""
    protocol: str
    checked: int = 0              # events replayed
    mismatches: List[Mismatch] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.mismatches

    @property
    def first(self) -> Optional[Mismatch]:
        return self.mismatches[0] if self.mismatches else None

    def summary(self) -> str:
        verdict = ("refines" if self.ok else
                   f"{len(self.mismatches)} non-refining step(s)")
        out = f"{self.protocol}: {self.checked} events — {verdict}"
        if self.notes:
            out += f" ({'; '.join(self.notes)})"
        return out

    def __str__(self) -> str:
        lines = [self.summary()]
        lines.extend(f"  {m}" for m in self.mismatches)
        return "\n".join(lines)


def check_kvpage(records: Sequence[Record],
                 require_drained: bool = False) -> ConformanceReport:
    """Replay ``kvpage`` events through the page-lifecycle rules of the
    :func:`~.protomodels.kv_lifecycle` model: every op must target a
    page the refcount state machine says it may, and the recorded
    refcount-after must equal the replayed one.  ``require_drained``
    additionally asserts the terminal invariant (pages-in-use == 0
    once the stream ends) — the no-leak property for runs that release
    everything before the dump."""
    rep = ConformanceReport(protocol="kv_lifecycle")
    live: Dict[Any, int] = {}     # pid -> replayed refcount

    def bad(i: int, ev: Record, reason: str) -> None:
        rep.mismatches.append(Mismatch(i, ev, reason))

    for i, ev in enumerate(records):
        if ev.get("key") != "kvpage":
            continue
        rep.checked += 1
        op = ev.get("phase")
        pid = ev.get("object")
        info = ev.get("info") or {}
        refs = info.get("refs")
        if op == "alloc":
            if pid in live:
                bad(i, ev, f"alloc of live page {pid} "
                    f"(refs={live[pid]})")
                continue
            live[pid] = 1
        elif op == "retain":
            if pid not in live:
                bad(i, ev, f"retain of freed page {pid}")
                continue
            live[pid] += 1
            if refs is not None and refs != live[pid]:
                bad(i, ev, f"refcount drift on retain: recorded "
                    f"{refs}, replay says {live[pid]} "
                    f"(a lifecycle event is missing)")
                live[pid] = refs            # resync: report first drift
        elif op == "release":
            if pid not in live:
                # KVPagePool.release is idempotent on freed pids by
                # contract — a no-op, not a protocol step
                continue
            live[pid] -= 1
            if refs is not None and refs != live[pid]:
                bad(i, ev, f"refcount drift on release: recorded "
                    f"{refs}, replay says {live[pid]}")
                live[pid] = refs
            if live[pid] < 0:
                bad(i, ev, f"refcount underflow on page {pid}")
                del live[pid]
        elif op == "free":
            if pid not in live:
                bad(i, ev, f"free of already-freed page {pid}")
                continue
            if live[pid] > 0:
                bad(i, ev, f"free of page {pid} with "
                    f"{live[pid]} live reference(s)")
            del live[pid]
        elif op == "cow":
            # annotation on an already-allocated copy: both ends live
            if pid not in live:
                bad(i, ev, f"cow produced unknown page {pid}")
            src = info.get("src")
            if src is not None and src not in live:
                bad(i, ev, f"cow of freed source page {src}")
        elif op == "write":
            # THE write-back-after-free oracle (PR 15's spec bug class)
            if pid not in live:
                bad(i, ev, f"write-back to freed page {pid} "
                    "(write-after-free)")
        else:
            bad(i, ev, f"unknown kvpage op {op!r}")

    if require_drained and live:
        rep.notes.append(
            f"stream ends with {len(live)} page(s) still in use: "
            f"{sorted(live)[:8]}")
        rep.mismatches.append(Mismatch(
            len(records), {"key": "kvpage", "phase": "<end>",
                           "object": None},
            f"pages-in-use != 0 at end of stream ({sorted(live)[:8]})"))
    return rep


def check_admission(records: Sequence[Record]) -> ConformanceReport:
    """Replay ``admission`` admit/retire/reconcile events through the
    window rules of :func:`~.protomodels.admission_budget`: depths
    never negative, never above the hard window, and the recorded
    depth-after always equals the replayed one."""
    rep = ConformanceReport(protocol="admission_budget")
    inflight: Dict[str, int] = {}         # tenant -> replayed depth
    windows: Dict[str, int] = {}

    def bad(i: int, ev: Record, reason: str) -> None:
        rep.mismatches.append(Mismatch(i, ev, reason))

    for i, ev in enumerate(records):
        if ev.get("key") != "admission":
            continue
        phase = ev.get("phase")
        if phase not in ("admit", "retire", "reconcile"):
            continue                      # park spans: latency, not steps
        rep.checked += 1
        info = ev.get("info") or {}
        ten = info.get("tenant", "?")
        rows = int(info.get("rows", 1))
        rec_depth = info.get("inflight")
        if phase == "admit":
            if "window" in info:
                windows[ten] = int(info["window"])
            cur = inflight.get(ten)
            if cur is None:
                # stream may open mid-life: adopt the recorded baseline
                cur = max(int(rec_depth) - rows, 0) \
                    if rec_depth is not None else 0
            new = cur + rows
            w = windows.get(ten)
            if w is not None and new > w:
                bad(i, ev, f"admit of {rows} rows puts tenant "
                    f"{ten!r} at depth {new} > hard window {w}")
            if rec_depth is not None and int(rec_depth) != new:
                bad(i, ev, f"depth drift on admit: recorded "
                    f"{rec_depth}, replay says {new}")
                new = int(rec_depth)
            inflight[ten] = new
        else:                              # retire / reconcile
            cur = inflight.get(ten)
            if cur is None:
                cur = int(rec_depth) + rows if rec_depth is not None \
                    else rows
            new = cur - rows
            if new < 0:
                bad(i, ev, f"retire of {rows} rows drives tenant "
                    f"{ten!r} depth negative ({new})")
                new = 0
            if rec_depth is not None and int(rec_depth) != new:
                bad(i, ev, f"depth drift on {phase}: recorded "
                    f"{rec_depth}, replay says {new}")
                new = int(rec_depth)
            inflight[ten] = new

    residual = {t: d for t, d in inflight.items() if d != 0}
    if residual:
        rep.notes.append(f"open depths at end of stream: {residual}")
    return rep


#: protocol name -> replay function over a record stream
PASSES = {
    "kv_lifecycle": check_kvpage,
    "admission": check_admission,
}


def replay(records: Sequence[Record],
           protocols: Optional[Sequence[str]] = None,
           ) -> List[ConformanceReport]:
    """Run every requested conformance pass (default: all whose events
    appear in the stream) and return the reports."""
    if protocols is None:
        keys = {ev.get("key") for ev in records}
        protocols = []
        if "kvpage" in keys:
            protocols.append("kv_lifecycle")
        if "admission" in keys:
            protocols.append("admission")
    out = []
    for name in protocols:
        if name not in PASSES:
            raise KeyError(f"unknown conformance pass {name!r}; have "
                           f"{', '.join(sorted(PASSES))}")
        out.append(PASSES[name](records))
    return out


def load_records(path: str) -> List[Record]:
    """Load an event stream dumped from :meth:`Trace.to_records` — a
    JSON list of record dicts, a dict with an ``events`` list (the
    ``dump_json`` envelope), or JSONL (one record dict per line)."""
    with open(path) as fh:
        text = fh.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        # line-delimited stream: ring dumps and `tee`d traces land here
        data = [json.loads(line) for line in text.splitlines() if line.strip()]
    if isinstance(data, dict):
        for key in ("events", "records", "traceEvents"):
            if key in data:
                data = data[key]
                break
        else:
            raise ValueError(f"{path}: no event list found")
    if not isinstance(data, list):
        raise ValueError(f"{path}: expected a JSON list of records")
    return data
