"""``python -m parsec_tpu.analysis`` — lint the shipped algorithms.

The CLI half of the hazard checker (the reference's ``--dot`` grapher +
ptgpp sanity checks rolled into one command):

- default: statically lint every shipped algorithm taskpool
  (potrf, getrf, getrf_left, geqrf, gemm, stencil) over a small tile
  grid and report findings; exit 1 if any error-severity finding fires
  (the shipped algorithms are the lint's zero-false-positive contract);
- ``--self-check``: additionally lint the seeded hazard fixtures
  (analysis/fixtures.py: racy, cyclic, undeclared producer, access
  violation, ...) and FAIL unless each is caught with an actionable
  message naming the task class, flow and coordinates; since ISSUE 14
  this arm also RUNS the seeded-WAW DTD fixture on both engines and
  fails unless ring-fed dfsan (native) reports it identically to the
  live sanitizer (Python);
- ``--dot PATH``: write the selected algorithm's instance DAG as DOT,
  edges colored by FlowAccess, hazard edges marked (grapher.py).

The default lint pass is purely static — no runtime context, no task
bodies; only the ``--self-check`` engine-parity arm starts a context.
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict, List


def _build_algorithms(nt: int) -> Dict[str, object]:
    """Small instances of the five shipped algorithm families (six
    taskpools — both LU variants), sized for full enumeration."""
    from ..algorithms import (build_gemm_ptg, build_geqrf, build_getrf,
                              build_getrf_left, build_potrf,
                              build_stencil_1d)
    from ..data import LocalCollection, TiledMatrix
    nb = 16
    sq = TiledMatrix(nt * nb, nt * nb, nb, nb, name="A")
    out = {
        "potrf": build_potrf(sq),
        "getrf": build_getrf(TiledMatrix(nt * nb, nt * nb, nb, nb,
                                         name="A")),
        "getrf_left": build_getrf_left(TiledMatrix(nt * nb, nt * nb, nb, nb,
                                                   name="A")),
        "geqrf": build_geqrf(TiledMatrix((nt + 1) * nb, nt * nb, nb, nb,
                                         name="A")),
        "gemm": build_gemm_ptg(TiledMatrix(nt * nb, nt * nb, nb, nb,
                                           name="A"),
                               TiledMatrix(nt * nb, nt * nb, nb, nb,
                                           name="B"),
                               TiledMatrix(nt * nb, nt * nb, nb, nb,
                                           name="C")),
        "stencil": build_stencil_1d(
            LocalCollection("X", {(i,): 0.0 for i in range(nt)}),
            n_tiles=nt, timesteps=max(nt - 1, 2)),
    }
    return out


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m parsec_tpu.analysis",
        description="static dataflow hazard lint over PTG taskpools")
    ap.add_argument("--algo", default="all",
                    help="algorithm to lint: all | potrf | getrf | "
                         "getrf_left | geqrf | gemm | stencil")
    ap.add_argument("--nt", type=int, default=4,
                    help="tile-grid size for the lint instances")
    ap.add_argument("--dot", default="",
                    help="write the (single) selected algorithm's DAG "
                         "as DOT with hazard edges marked")
    ap.add_argument("--self-check", action="store_true",
                    help="also lint the seeded hazard fixtures and fail "
                         "unless every one is caught")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="print every finding, not just summaries")
    args = ap.parse_args(argv)

    pools = _build_algorithms(args.nt)
    if args.algo != "all":
        if args.algo not in pools:
            ap.error(f"unknown algorithm {args.algo!r}; have "
                     f"{', '.join(sorted(pools))}")
        pools = {args.algo: pools[args.algo]}

    rc = 0
    last_report = None
    for name, tp in sorted(pools.items()):
        report = tp.validate(mode="none")    # lint only, never raise
        last_report = report
        status = "clean" if not report.findings else \
            f"{len(report.errors)} errors, {len(report.warnings)} warnings"
        print(f"[lint] {name}: {report.summary()} — {status}")
        if args.verbose or report.findings:
            for f in report.findings:
                print(f"       {f}")
        if report.errors:
            rc = 1

    if args.dot:
        if len(pools) != 1:
            print("[dot] --dot needs a single --algo selection",
                  file=sys.stderr)
            return 2
        with open(args.dot, "w") as fh:
            fh.write(last_report.to_dot())
        print(f"[dot] wrote {args.dot}")

    if args.self_check:
        from .fixtures import native_self_check, self_check
        failures, lines = self_check()
        # ISSUE 14: the seeded DTD WAW must be reported identically by
        # the live sanitizer (Python engine) and the ring-fed replay
        # (native engine) — this arm RUNS both engines, it is not
        # static like the fixtures above
        nfail, nlines = native_self_check()
        failures += nfail
        lines += nlines
        for line in lines:
            print(f"[self-check] {line}")
        if failures:
            print(f"[self-check] FAILED: {failures} fixture(s) not caught")
            rc = 1
        else:
            print("[self-check] all seeded hazards caught")

    print("OK" if rc == 0 else "FAILED")
    return rc


if __name__ == "__main__":
    sys.exit(main())
