"""``python -m parsec_tpu.analysis`` — lint the shipped algorithms.

The CLI half of the hazard checker (the reference's ``--dot`` grapher +
ptgpp sanity checks rolled into one command):

- default: statically lint every shipped algorithm taskpool
  (potrf, getrf, getrf_left, geqrf, gemm, stencil) over a small tile
  grid and report findings; exit 1 if any error-severity finding fires
  (the shipped algorithms are the lint's zero-false-positive contract);
- ``--self-check``: additionally lint the seeded hazard fixtures
  (analysis/fixtures.py: racy, cyclic, undeclared producer, access
  violation, ...) and FAIL unless each is caught with an actionable
  message naming the task class, flow and coordinates; since ISSUE 14
  this arm also RUNS the seeded-WAW DTD fixture on both engines and
  fails unless ring-fed dfsan (native) reports it identically to the
  live sanitizer (Python);
- ``--dot PATH``: write the selected algorithm's instance DAG as DOT,
  edges colored by FlowAccess, hazard edges marked (grapher.py).

The default lint pass is purely static — no runtime context, no task
bodies; only the ``--self-check`` engine-parity arm starts a context.

Since ISSUE 19 the CLI also fronts the protocol checker::

    python -m parsec_tpu.analysis protocheck [model] [--bound N]
                                             [--trace FILE] [--seeded]

- no model argument: check every registered current-protocol model
  (analysis/protomodels.py) and fail on any violation — the shipped
  protocols are the checker's zero-violation contract;
- ``--seeded``: additionally run the seeded pre-fix variants and FAIL
  unless each is caught with its expected rule (the checker checking
  itself, same contract shape as ``--self-check``);
- ``--trace FILE``: replay a dumped event stream (``Trace.dump_json``
  or a raw ``to_records`` list) through the conformance passes and
  report the first non-refining step.
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict, List


def _build_algorithms(nt: int) -> Dict[str, object]:
    """Small instances of the five shipped algorithm families (six
    taskpools — both LU variants), sized for full enumeration."""
    from ..algorithms import (build_gemm_ptg, build_geqrf, build_getrf,
                              build_getrf_left, build_potrf,
                              build_stencil_1d)
    from ..data import LocalCollection, TiledMatrix
    nb = 16
    sq = TiledMatrix(nt * nb, nt * nb, nb, nb, name="A")
    out = {
        "potrf": build_potrf(sq),
        "getrf": build_getrf(TiledMatrix(nt * nb, nt * nb, nb, nb,
                                         name="A")),
        "getrf_left": build_getrf_left(TiledMatrix(nt * nb, nt * nb, nb, nb,
                                                   name="A")),
        "geqrf": build_geqrf(TiledMatrix((nt + 1) * nb, nt * nb, nb, nb,
                                         name="A")),
        "gemm": build_gemm_ptg(TiledMatrix(nt * nb, nt * nb, nb, nb,
                                           name="A"),
                               TiledMatrix(nt * nb, nt * nb, nb, nb,
                                           name="B"),
                               TiledMatrix(nt * nb, nt * nb, nb, nb,
                                           name="C")),
        "stencil": build_stencil_1d(
            LocalCollection("X", {(i,): 0.0 for i in range(nt)}),
            n_tiles=nt, timesteps=max(nt - 1, 2)),
    }
    return out


def _protocheck_main(argv: List[str]) -> int:
    """``protocheck`` subcommand: model checking + trace conformance."""
    from . import conformance, protomodels
    from .protocheck import check

    ap = argparse.ArgumentParser(
        prog="python -m parsec_tpu.analysis protocheck",
        description="explicit-state protocol checking over the serving "
                    "runtime's admission/KV/wfq/termdet protocols")
    ap.add_argument("model", nargs="?", default="all",
                    help="protocol model to check: all | "
                         + " | ".join(sorted(protomodels.MODELS)))
    ap.add_argument("--bound", type=int, default=20000,
                    help="state-count bound for the exploration "
                         "(exceeding it skips liveness and notes "
                         "TRUNCATED)")
    ap.add_argument("--seeded", action="store_true",
                    help="also check the seeded pre-fix variants and "
                         "fail unless each is caught with its expected "
                         "rule")
    ap.add_argument("--trace", default="",
                    help="replay a dumped Trace event stream (JSON) "
                         "through the conformance passes")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="print counterexample traces even on success "
                         "paths")
    args = ap.parse_args(argv)

    rc = 0
    names = sorted(protomodels.MODELS) if args.model == "all" \
        else [args.model]
    for name in names:
        if name not in protomodels.MODELS:
            ap.error(f"unknown model {name!r}; have "
                     f"{', '.join(sorted(protomodels.MODELS))}")
        report = check(protomodels.MODELS[name](), bound=args.bound)
        status = "clean" if report.ok else \
            f"{len(report.errors)} violation(s)"
        print(f"[protocheck] {report.summary()} — {status}")
        if report.findings:
            for f in report.findings:
                print("\n".join(f"    {ln}"
                                for ln in str(f).splitlines()))
        if not report.ok:
            rc = 1

    if args.seeded:
        for name, (mk, rule) in sorted(protomodels.SEEDED.items()):
            report = check(mk(), bound=args.bound)
            hit = [f for f in report.errors
                   if f.rule == rule or f.rule.startswith(rule)]
            if hit:
                print(f"[protocheck] seeded {name}: caught "
                      f"({hit[0].rule}, {len(hit[0].trace)}-line "
                      f"counterexample)")
                if args.verbose:
                    print("\n".join(f"    {ln}"
                                    for ln in str(hit[0]).splitlines()))
            else:
                print(f"[protocheck] seeded {name}: NOT caught "
                      f"(expected {rule}, got "
                      f"{[f.rule for f in report.errors] or 'nothing'})")
                rc = 1

    if args.trace:
        records = conformance.load_records(args.trace)
        reports = conformance.replay(records)
        if not reports:
            print(f"[conformance] {args.trace}: no replayable events "
                  "(kvpage/admission)")
        for rep in reports:
            print(f"[conformance] {rep.summary()}")
            for m in rep.mismatches:
                print(f"    {m}")
            if not rep.ok:
                rc = 1

    print("OK" if rc == 0 else "FAILED")
    return rc


def main(argv: List[str] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "protocheck":
        return _protocheck_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m parsec_tpu.analysis",
        description="static dataflow hazard lint over PTG taskpools")
    ap.add_argument("--algo", default="all",
                    help="algorithm to lint: all | potrf | getrf | "
                         "getrf_left | geqrf | gemm | stencil")
    ap.add_argument("--nt", type=int, default=4,
                    help="tile-grid size for the lint instances")
    ap.add_argument("--dot", default="",
                    help="write the (single) selected algorithm's DAG "
                         "as DOT with hazard edges marked")
    ap.add_argument("--self-check", action="store_true",
                    help="also lint the seeded hazard fixtures and fail "
                         "unless every one is caught")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="print every finding, not just summaries")
    args = ap.parse_args(argv)

    pools = _build_algorithms(args.nt)
    if args.algo != "all":
        if args.algo not in pools:
            ap.error(f"unknown algorithm {args.algo!r}; have "
                     f"{', '.join(sorted(pools))}")
        pools = {args.algo: pools[args.algo]}

    rc = 0
    last_report = None
    for name, tp in sorted(pools.items()):
        report = tp.validate(mode="none")    # lint only, never raise
        last_report = report
        status = "clean" if not report.findings else \
            f"{len(report.errors)} errors, {len(report.warnings)} warnings"
        print(f"[lint] {name}: {report.summary()} — {status}")
        if args.verbose or report.findings:
            for f in report.findings:
                print(f"       {f}")
        if report.errors:
            rc = 1

    if args.dot:
        if len(pools) != 1:
            print("[dot] --dot needs a single --algo selection",
                  file=sys.stderr)
            return 2
        with open(args.dot, "w") as fh:
            fh.write(last_report.to_dot())
        print(f"[dot] wrote {args.dot}")

    if args.self_check:
        from .fixtures import native_self_check, self_check
        failures, lines = self_check()
        # ISSUE 14: the seeded DTD WAW must be reported identically by
        # the live sanitizer (Python engine) and the ring-fed replay
        # (native engine) — this arm RUNS both engines, it is not
        # static like the fixtures above
        nfail, nlines = native_self_check()
        failures += nfail
        lines += nlines
        # ISSUE 19: the seeded pre-fix protocol models are part of the
        # same contract — each must be caught with its expected rule
        from . import protomodels
        from .protocheck import check as proto_check
        for pname, (mk, rule) in sorted(protomodels.SEEDED.items()):
            report = proto_check(mk(), bound=20000)
            hit = [f for f in report.errors
                   if f.rule == rule or f.rule.startswith(rule)]
            if hit:
                lines.append(f"ok   protocheck {pname}: {hit[0].rule} "
                             f"({len(hit[0].trace)}-line counterexample)")
            else:
                failures += 1
                lines.append(f"FAIL protocheck {pname}: expected {rule},"
                             f" got {[f.rule for f in report.errors]}")
        for line in lines:
            print(f"[self-check] {line}")
        if failures:
            print(f"[self-check] FAILED: {failures} fixture(s) not caught")
            rc = 1
        else:
            print("[self-check] all seeded hazards caught")

    print("OK" if rc == 0 else "FAILED")
    return rc


if __name__ == "__main__":
    sys.exit(main())
