"""parsec_tpu.analysis — dataflow hazard checker + runtime race sanitizer.

Two cooperating halves audit that a taskpool's dependency declarations
fully determine its execution order (the PaRSEC correctness claim):

- **Static DAG lint** (:mod:`~parsec_tpu.analysis.lint` over
  :mod:`~parsec_tpu.analysis.model`): symbolically enumerates a PTG/JDF
  taskpool's flow specs and reports undeclared producers, WAW/WAR
  hazards, access-mode violations, dangling outputs, dependency cycles
  and owner-computes affinity mismatches.  Exposed as
  ``taskpool.validate()``, the ``analysis.lint = off|warn|error`` MCA
  knob (checked at taskpool registration), and the
  ``python -m parsec_tpu.analysis`` CLI.
- **Runtime race sanitizer** (:mod:`~parsec_tpu.analysis.dfsan`, the
  ``dfsan`` PINS module): FastTrack-style vector clocks over every tile
  access observed through the release paths, striped-lock order
  tracking, and a per-tile version-sequence determinism digest guarding
  the scheduler/release fast paths.

A third half (ISSUE 19) audits the *protocols between* the concurrent
parties rather than any one DAG:

- **Protocol checker** (:mod:`~parsec_tpu.analysis.protocheck` over
  :mod:`~parsec_tpu.analysis.protomodels`): SPIN-style explicit-state
  exploration of the admission/KV-lifecycle/wfq-lane/termdet protocols
  — invariants, deadlock, circular wait in the resource-allocation
  graph, and fair-lasso starvation, each with a shortest
  counterexample trace.  :mod:`~parsec_tpu.analysis.conformance`
  replays recorded Trace/native-ring event streams through the same
  models and reports the first non-refining step.  CLI:
  ``python -m parsec_tpu.analysis protocheck``.

Reference counterparts: jdf_sanity_checks (jdf.c), the grapher/DOT
tooling (parsec_prof_grapher.c) and the iterators_checker PINS module.
"""

from __future__ import annotations

from ..utils import mca_param

mca_param.register(
    "analysis.lint", "off", choices=("off", "warn", "error"),
    help="static dataflow lint at taskpool registration: off | warn "
         "(log findings) | error (refuse taskpools with error-severity "
         "findings)")
mca_param.register(
    "analysis.lint_max_tasks", 20000,
    help="instance-enumeration cap for the lint; larger task spaces "
         "degrade to structural (per-class) checks only")

from .lint import (Finding, HazardError, LintReport, lint_hot_config,
                   lint_taskpool, validate)
from .model import Model, build_model
from .dfsan import DataflowSanitizer, RaceReport
from .protocheck import (Action, Liveness, ProtoFinding, ProtoModel,
                         ProtoReport, check)
from .conformance import ConformanceReport, load_records, replay

__all__ = [
    "Finding", "HazardError", "LintReport", "lint_taskpool", "validate",
    "lint_hot_config", "Model", "build_model", "DataflowSanitizer",
    "RaceReport", "Action", "Liveness", "ProtoFinding", "ProtoModel",
    "ProtoReport", "check", "ConformanceReport", "load_records", "replay",
]
