"""Static dataflow lint over PTG/JDF taskpools.

PaRSEC's correctness story rests on the JDF/PTG dependency declarations
fully determining the execution order; the reference audits the claim
with ``jdf_sanity_checks`` (jdf.c) at compile time, the grapher/DOT
output, and the iterators_checker PINS module at runtime.  This module
is the static half of that tooling here: it materializes the bounded
instance DAG (analysis/model.py) and reports, with the exact task
class, flow and coordinates:

- **undeclared-producer** — an ``In(src=...)`` edge whose named source
  instance does not exist, or whose flow never emits to this consumer;
- **waw-hazard** — two *unordered* task instances both write the same
  collection tile (the final tile value is schedule-dependent);
- **war-hazard** — a collection read unordered against a writer of the
  same tile (the reader may observe either version);
- **access-violation** — data flowing through a flow whose declared
  :class:`~parsec_tpu.core.task.FlowAccess` forbids it (CTL flows
  carrying payloads, terminal write-backs through READ flows, reads
  into WRITE-only flows) — the static cross-check of the WRITE/RW
  return-arity contract ``core/task.py`` documents (the dynamic half
  lives in analysis/dfsan.py);
- **cycle** — a dependency cycle among task instances (the taskpool can
  never quiesce);
- **phantom-target** / **ambiguous-guards** — an ``Out`` aimed at a
  nonexistent class/instance; overlapping In guards;
- **dangling-output** (warning) — a produced WRITE/RW value that no
  active dep consumes or writes back (silently dropped — suppressed for
  flows tiled onto ``scratch`` collections, which are intra-DAG
  temporaries by declaration);
- **affinity-mismatch** (warning) — owner-computes violations: a task
  terminally writes tiles but its affinity names none of them, forcing
  an avoidable remote write-back.

Entry points: :func:`lint_taskpool`, ``Taskpool.validate()`` (method on
the core taskpool), the ``analysis.lint = off|warn|error`` MCA knob
checked at taskpool registration, and ``python -m parsec_tpu.analysis``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..core.task import FlowAccess
from .model import Model, _norm, build_model

ERROR = "error"
WARNING = "warning"
NOTE = "note"


@dataclass
class Finding:
    """One lint finding, anchored to a task instance / flow / tile."""
    rule: str
    severity: str
    task: str                  # "CLASS(coords)" primary site
    flow: str = ""
    tile: str = ""
    message: str = ""
    # for hazard findings: the second task instance of the unordered pair
    other: str = ""

    def __str__(self) -> str:
        return f"[{self.severity}] {self.rule}: {self.message}"


@dataclass
class LintReport:
    """All findings of one lint run plus the model they refer to."""
    taskpool: str
    findings: List[Finding] = field(default_factory=list)
    model: Optional[Model] = None
    truncated: bool = False
    skipped_classes: List[str] = field(default_factory=list)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    @property
    def ok(self) -> bool:
        return not self.errors

    def by_rule(self, rule: str) -> List[Finding]:
        return [f for f in self.findings if f.rule == rule]

    def summary(self) -> str:
        n = len(self.model.nodes) if self.model is not None else 0
        parts = [f"{self.taskpool}: {n} task instances",
                 f"{len(self.errors)} errors",
                 f"{len(self.warnings)} warnings"]
        if self.truncated:
            parts.append("TRUNCATED (analysis.lint_max_tasks)")
        if self.skipped_classes:
            parts.append(f"skipped non-PTG classes: "
                         f"{', '.join(self.skipped_classes)}")
        return "; ".join(parts)

    def __str__(self) -> str:
        lines = [self.summary()]
        lines.extend(f"  {f}" for f in self.findings)
        return "\n".join(lines)

    # -- visual report ------------------------------------------------------
    def to_dot(self) -> str:
        """DOT rendering of the instance DAG with edges colored by
        FlowAccess and hazard edges marked — the lint's visual report
        (profiling/grapher.py does the rendering; satellite of the
        reference's --dot grapher)."""
        from ..profiling.grapher import Grapher
        gr = Grapher()
        if self.model is None:
            return gr.to_dot()
        for node in self.model.nodes:
            gr.add_node(node.label, node.tc.name)
        for e in self.model.edges:
            access = self.model.nodes[e.dst].tc.flow_by_name[e.dst_flow].access
            gr.add_edge(self.model.nodes[e.src].label,
                        self.model.nodes[e.dst].label,
                        e.dst_flow, access)
        for f in self.findings:
            if f.rule in ("waw-hazard", "war-hazard") and f.other:
                gr.mark_hazard(f.task, f.other, f.flow, f.rule)
            elif f.rule == "cycle" and f.other:
                gr.mark_hazard(f.task, f.other, f.flow, f.rule)
        return gr.to_dot()


class HazardError(ValueError):
    """Raised by ``taskpool.validate()`` / the ``analysis.lint=error``
    registration check when the lint reports error-severity findings."""

    def __init__(self, report: LintReport):
        super().__init__(str(report))
        self.report = report


def _tile_str(tk: Tuple[str, Tuple]) -> str:
    return f"{tk[0]}{tk[1]}"


def _check_structural(tp, report: LintReport) -> None:
    """Per-class spec checks that need no instance enumeration (always
    run, even past the lint_max_tasks cap)."""
    for tc in tp.task_classes:
        for spec in getattr(tc, "spec_list", ()):
            is_ctl = bool(spec.access & FlowAccess.CTL)
            writes = bool(spec.access & FlowAccess.WRITE)
            reads = bool(spec.access & FlowAccess.READ)
            for dep in spec.ins:
                if is_ctl and (dep.data is not None or dep.new is not None):
                    report.findings.append(Finding(
                        "access-violation", ERROR, tc.name, spec.name,
                        message=f"{tc.name}.{spec.name}: CTL flow declares "
                                f"a data/NEW input — control flows carry "
                                f"no payload"))
                if writes and not reads and not is_ctl and (
                        dep.src is not None or dep.data is not None):
                    report.findings.append(Finding(
                        "access-violation", ERROR, tc.name, spec.name,
                        message=f"{tc.name}.{spec.name}: WRITE-only flow "
                                f"consumes an input value (declare RW, or "
                                f"use NEW for a fresh value)"))
            for dep in spec.outs:
                if is_ctl and dep.data is not None:
                    report.findings.append(Finding(
                        "access-violation", ERROR, tc.name, spec.name,
                        message=f"{tc.name}.{spec.name}: CTL flow declares "
                                f"a terminal collection write-back"))
                if reads and not writes and not is_ctl and \
                        dep.data is not None:
                    report.findings.append(Finding(
                        "access-violation", ERROR, tc.name, spec.name,
                        message=f"{tc.name}.{spec.name}: READ flow declares "
                                f"a terminal collection write-back — the "
                                f"body cannot produce a value for it "
                                f"(core.task: only WRITE/RW flows are "
                                f"output flows)"))


def _check_undeclared_producers(m: Model, report: LintReport) -> None:
    g = m.taskpool.g
    for node in m.nodes:
        tc, p = node.tc, node.coords
        for spec in tc.spec_list:
            try:
                dep = tc._active_in(g, spec, p)
            except RuntimeError:
                continue        # already reported as ambiguous-guards
            if dep is None or dep.src is None:
                continue
            src_cls, src_params_fn, src_flow = dep.src
            sp = src_params_fn(g, *p)
            if dep.gather:
                raw = [sp] if isinstance(sp, tuple) else sp
                coords = sorted({_norm(c) for c in raw})
            else:
                coords = [_norm(sp)]
            for coord in coords:
                src_label = f"{src_cls}({', '.join(map(str, coord))})"
                src_idx = m.index.get((src_cls, coord))
                if src_idx is None:
                    report.findings.append(Finding(
                        "undeclared-producer", ERROR, node.label, spec.name,
                        message=f"{node.label}.{spec.name} <- "
                                f"{src_label}.{src_flow}: the named "
                                f"producer instance does not exist"))
                    continue
                if (src_idx, src_flow, node.idx, spec.name) not in m.produced:
                    report.findings.append(Finding(
                        "undeclared-producer", ERROR, node.label, spec.name,
                        other=m.nodes[src_idx].label,
                        message=f"{node.label}.{spec.name} <- "
                                f"{src_label}.{src_flow}: the producer "
                                f"exists but its flow {src_flow!r} never "
                                f"emits to {node.label}.{spec.name} (no "
                                f"matching Out declaration)"))


def _check_dangling_outputs(m: Model, report: LintReport) -> None:
    g = m.taskpool.g
    for node in m.nodes:
        tc, p = node.tc, node.coords
        for spec in tc.spec_list:
            if not (spec.access & FlowAccess.WRITE) or \
                    (spec.access & FlowAccess.CTL):
                continue
            if any(dep.active(g, p) for dep in spec.outs):
                continue
            # scratch-tiled flows are intra-DAG temporaries: dropping the
            # last wave's value is their declared contract
            if spec.tile is not None:
                dc, _key = spec.tile(g, *p)
                if getattr(dc, "scratch", False):
                    continue
            report.findings.append(Finding(
                "dangling-output", WARNING, node.label, spec.name,
                message=f"{node.label}.{spec.name}: WRITE flow has no "
                        f"active output dep — the produced value is "
                        f"silently dropped"))


def _check_hazards(m: Model, report: LintReport) -> None:
    """WAW (unordered writers) and WAR/RAW (read unordered with a write)
    hazards per collection tile. Writers of one tile must form a total
    order: checking consecutive pairs of a topological linearization is
    sufficient — any unordered pair leaves some consecutive pair
    unordered."""
    order, _ = m.topo_order()
    topo_pos = {idx: i for i, idx in enumerate(order)}

    def pos(i: int) -> int:
        return topo_pos.get(i, len(m.nodes))

    for tk, accs in m.writes.items():
        writers = sorted({a.node for a in accs}, key=pos)
        flow_of = {a.node: a.flow for a in accs}
        for a, b in zip(writers, writers[1:]):
            if not m.ordered(a, b):
                report.findings.append(Finding(
                    "waw-hazard", ERROR, m.nodes[a].label,
                    flow_of[a], _tile_str(tk), other=m.nodes[b].label,
                    message=f"WAW hazard on tile {_tile_str(tk)}: "
                            f"{m.nodes[a].label}.{flow_of[a]} and "
                            f"{m.nodes[b].label}.{flow_of[b]} both write "
                            f"it with no dependency path ordering them — "
                            f"the final value is schedule-dependent"))
        readers = m.reads.get(tk, ())
        for r in readers:
            for w in writers:
                if w == r.node:
                    continue
                if not m.ordered(r.node, w):
                    report.findings.append(Finding(
                        "war-hazard", ERROR, m.nodes[r.node].label,
                        r.flow, _tile_str(tk), other=m.nodes[w].label,
                        message=f"read/write hazard on tile "
                                f"{_tile_str(tk)}: "
                                f"{m.nodes[r.node].label}.{r.flow} reads "
                                f"it unordered against writer "
                                f"{m.nodes[w].label}.{flow_of[w]} — the "
                                f"reader may observe either version"))


def _check_cycles(m: Model, report: LintReport) -> None:
    cyc = m.find_cycle()
    if cyc is None:
        return
    labels = [m.nodes[i].label for i in cyc]
    report.findings.append(Finding(
        "cycle", ERROR, labels[0], other=labels[1] if len(labels) > 1 else "",
        message=f"dependency cycle: {' -> '.join(labels)} — these tasks "
                f"can never become ready (deps_goal unreachable)"))


def _check_affinity(m: Model, report: LintReport) -> None:
    """Owner-computes: a task's affinity tile should be one the task
    actually works on (any flow's declared tile, read or write) —
    placing it elsewhere makes EVERY data movement remote.  A terminal
    write landing off-affinity is fine when the task also works on its
    affinity tile (pipeline hand-offs like geqrf TSMQR's row tile)."""
    for idx, aff in m.node_affinity.items():
        written = m.node_writes.get(idx)
        if not written:
            continue
        touched = m.node_touch.get(idx, ())
        if aff in written or aff in touched:
            continue
        node = m.nodes[idx]
        report.findings.append(Finding(
            "affinity-mismatch", WARNING, node.label, tile=_tile_str(aff),
            message=f"{node.label}: owner-computes mismatch — affinity "
                    f"places the task on {_tile_str(aff)}, a tile it "
                    f"never touches, while it terminally writes "
                    f"{', '.join(_tile_str(t) for t in written)}; every "
                    f"transfer becomes remote"))


def lint_taskpool(tp, max_tasks: int = 0) -> LintReport:
    """Run every static check over ``tp`` and return the report.

    Works on any core taskpool; task classes without closed-form PTG
    specs (DTD, hand-built vtables) are listed in
    ``report.skipped_classes`` — their ordering is runtime state, which
    the dynamic sanitizer (analysis/dfsan.py) covers instead.
    """
    report = LintReport(taskpool=tp.name)
    _check_structural(tp, report)
    m = build_model(tp, max_tasks=max_tasks)
    report.model = m
    report.truncated = m.truncated
    report.skipped_classes = m.skipped_classes
    for rule, task, flow, msg in m.problems:
        report.findings.append(Finding(rule, ERROR, task, flow, message=msg))
    if m.truncated:
        report.findings.append(Finding(
            "truncated", NOTE, tp.name,
            message=f"{tp.name}: task space exceeds analysis.lint_max_tasks"
                    f" — instance-level checks skipped (structural checks "
                    f"still ran); raise the MCA param to lint fully"))
        return report
    if not m.nodes:
        return report
    _check_undeclared_producers(m, report)
    _check_dangling_outputs(m, report)
    _check_cycles(m, report)
    _check_hazards(m, report)
    _check_affinity(m, report)
    return report


def validate(tp, mode: str = "error", max_tasks: int = 0) -> LintReport:
    """``taskpool.validate()`` implementation (core/taskpool.py binds
    it): lint and, per ``mode``, raise :class:`HazardError` on errors
    (``"error"``) or log them (``"warn"``)."""
    report = lint_taskpool(tp, max_tasks=max_tasks)
    if mode == "error" and not report.ok:
        raise HazardError(report)
    if mode == "warn" and report.findings:
        from ..utils.debug import warning
        for f in report.findings:
            warning("analysis", "%s", f)
    return report


# ---------------------------------------------------------------------------
# hot-path config-lookup lint (source-level, AST)
# ---------------------------------------------------------------------------

#: scheduler entry points that run once per task on every worker — an
#: uncached registry read here is a cross-worker serialization point
#: (PR 15 found exactly this in wfq select(): the full mca_param.get
#: takes the global registry lock and re-resolves the environment)
_HOT_FUNCS = frozenset({"select", "steal", "try_steal", "schedule",
                        "pop_front", "pop_back"})

#: mca_param entry points that are SAFE on the hot path
_CACHED_READS = frozenset({"cached_get"})


def _scan_hot_config_source(src: str, filename: str) -> List[Finding]:
    """AST scan of one source file for uncached ``mca_param.get`` /
    ``mca_param.registry`` calls on hot paths: anywhere inside a
    scheduler hot function (``_HOT_FUNCS``), or inside any loop of any
    other function (the worker-main shape — a one-time read in the
    preamble is fine, the same read per loop iteration is not)."""
    import ast
    findings: List[Finding] = []
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as exc:
        findings.append(Finding(
            "hot-config-read", NOTE, filename,
            message=f"{filename}: unparseable, skipped ({exc})"))
        return findings

    def is_config_read(call: "ast.Call") -> Optional[str]:
        fn = call.func
        if not isinstance(fn, ast.Attribute):
            return None
        if fn.attr in _CACHED_READS:
            return None
        if fn.attr not in ("get", "registry"):
            return None
        base = fn.value
        if isinstance(base, ast.Name) and base.id == "mca_param":
            return f"mca_param.{fn.attr}"
        if isinstance(base, ast.Attribute) and base.attr == "mca_param":
            return f"mca_param.{fn.attr}"
        return None

    def scan_func(fn_node, qual: str) -> None:
        hot_everywhere = fn_node.name in _HOT_FUNCS
        # (node, loop_depth) walk that does NOT descend into nested
        # function definitions (they get their own scan_func pass)
        stack = [(child, 0) for child in fn_node.body]
        while stack:
            node, depth = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            d = depth + 1 if isinstance(
                node, (ast.For, ast.While, ast.AsyncFor)) else depth
            if isinstance(node, ast.Call):
                read = is_config_read(node)
                if read is not None and (hot_everywhere or depth > 0):
                    where = ("scheduler hot function" if hot_everywhere
                             else "loop body")
                    findings.append(Finding(
                        "hot-config-read", ERROR,
                        f"{qual} ({filename}:{node.lineno})",
                        message=f"{filename}:{node.lineno}: {read} in "
                                f"{where} {qual}() — a full registry "
                                f"read (global lock + env resolve) "
                                f"once per task serializes the "
                                f"workers; hoist it or use "
                                f"mca_param.cached_get"))
            for child in ast.iter_child_nodes(node):
                stack.append((child, d))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_func(node, node.name)
    return findings


def lint_hot_config(paths: Optional[List[str]] = None) -> List[Finding]:
    """Scan the scheduler package and the worker loop (the shipped hot
    paths) — or an explicit file list — for uncached config reads.
    The shipped tree is the rule's zero-false-positive contract
    (enforced by the analysis CLI self-check)."""
    import glob
    import os
    if paths is None:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(pkg, "sched", "*.py")))
        paths.append(os.path.join(pkg, "core", "context.py"))
    findings: List[Finding] = []
    for path in paths:
        try:
            with open(path) as fh:
                src = fh.read()
        except OSError as exc:
            findings.append(Finding(
                "hot-config-read", NOTE, path,
                message=f"{path}: unreadable, skipped ({exc})"))
            continue
        findings.extend(
            _scan_hot_config_source(src, os.path.basename(path)))
    return findings
