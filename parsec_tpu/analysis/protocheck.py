"""Explicit-state protocol checker (SPIN's nested-search core, scaled
to the runtime's protocol sizes).

The dataflow lint (analysis/lint.py) proves properties of ONE taskpool
DAG; the bugs that actually cost review cycles in PRs 8 and 15 were
*protocol* bugs between concurrent parties — admission windows vs the
KV page budget, spec-branch cancellation vs in-flight write-backs,
prefill-lane cadence vs an adversarial arrival order.  This module is
the checker for that class, in the style of Holzmann's SPIN: a protocol
is a guarded-command state machine (:class:`ProtoModel`), the checker
enumerates every reachable state of a bounded instance by BFS (so
counterexamples are *shortest*), and reports:

- **invariant** violations — a reachable state where a safety predicate
  fails (checked per state; ``terminal_invariants`` only on quiesced
  states, e.g. "pages-in-use == 0 at end of run");
- **deadlock** — a reachable non-terminal state with no enabled action;
- **circular-wait** — a cycle in the model's resource-allocation graph
  (``waits_for``), the lockdep-style acquire/hold analysis that catches
  budget deadlocks even when a timeout would mask the hang;
- **starvation** — a fair lasso: a reachable cycle along which a lane
  stays ``pending`` and no ``progress`` action ever fires, that weak
  fairness cannot rule out (an action enabled at *every* state of the
  cycle must fire on it; intermittently-enabled actions may be starved
  forever — exactly how interleave<=1 starved the prefill lane).

Every finding carries a rendered counterexample trace (init state,
action per step, violating state) in the ``LintReport`` house style.
Models live in analysis/protomodels.py; trace-refinement against the
live engines in analysis/conformance.py.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Sequence, Set, Tuple)

from .lint import ERROR, NOTE, WARNING  # shared severity vocabulary

State = Dict[str, Any]


@dataclass(frozen=True)
class Action:
    """One guarded command: ``guard(state) -> bool`` and
    ``effect(state) -> state | [state, ...]`` (the effect receives a
    private copy and may mutate it; returning a list models internal
    nondeterminism).  ``fair=True`` declares weak fairness: a run that
    keeps the action continuously enabled must eventually take it
    (scheduler/worker steps are fair; environment arrivals are not)."""
    name: str
    guard: Callable[[State], bool]
    effect: Callable[[State], Any]
    fair: bool = False


@dataclass(frozen=True)
class Liveness:
    """Starvation-freedom spec: while ``pending(state)`` holds, some
    action in ``progress`` must eventually fire (under weak fairness
    of the model's ``fair`` actions)."""
    name: str
    pending: Callable[[State], bool]
    progress: frozenset


@dataclass
class ProtoModel:
    """A protocol as a guarded-command state machine."""
    name: str
    init: Callable[[], Any]                    # state dict or list of them
    actions: List[Action]
    invariants: List[Tuple[str, Callable[[State], bool]]] = \
        field(default_factory=list)
    terminal: Optional[Callable[[State], bool]] = None
    terminal_invariants: List[Tuple[str, Callable[[State], bool]]] = \
        field(default_factory=list)
    # resource-allocation graph: waits_for(state) -> [(waiter, holder)]
    waits_for: Optional[Callable[[State], List[Tuple[str, str]]]] = None
    liveness: List[Liveness] = field(default_factory=list)
    # optional compact state renderer for counterexample traces
    render: Optional[Callable[[State], str]] = None

    def render_state(self, s: State) -> str:
        if self.render is not None:
            return self.render(s)
        return " ".join(f"{k}={s[k]!r}" for k in sorted(s))


@dataclass
class ProtoFinding:
    """One protocol violation with its counterexample trace."""
    rule: str
    severity: str
    model: str
    message: str
    trace: List[str] = field(default_factory=list)

    def __str__(self) -> str:
        head = f"[{self.severity}] {self.rule}: {self.message}"
        if not self.trace:
            return head
        return head + "\n" + "\n".join(f"    {ln}" for ln in self.trace)


@dataclass
class ProtoReport:
    """All findings of one check() run plus exploration statistics."""
    model: str
    findings: List[ProtoFinding] = field(default_factory=list)
    states: int = 0
    transitions: int = 0
    elapsed_s: float = 0.0
    truncated: bool = False
    liveness_checked: bool = True

    @property
    def errors(self) -> List[ProtoFinding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> List[ProtoFinding]:
        return [f for f in self.findings if f.severity == WARNING]

    @property
    def ok(self) -> bool:
        return not self.errors

    def by_rule(self, rule: str) -> List[ProtoFinding]:
        return [f for f in self.findings if f.rule == rule]

    def summary(self) -> str:
        parts = [f"{self.model}: {self.states} states",
                 f"{self.transitions} transitions",
                 f"{len(self.errors)} errors",
                 f"{len(self.warnings)} warnings"]
        if self.truncated:
            parts.append("TRUNCATED (--bound; liveness skipped)")
        return "; ".join(parts)

    def __str__(self) -> str:
        lines = [self.summary()]
        lines.extend(f"  {f}" for f in self.findings)
        return "\n".join(lines)


def _freeze(v: Any) -> Any:
    """Canonical hashable form of a state value (dict insertion order
    and list/set identity must not split equivalent states)."""
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return tuple(sorted((_freeze(x) for x in v), key=repr))
    return v


def _copy_state(s: State) -> State:
    out = {}
    for k, v in s.items():
        if isinstance(v, list):
            v = list(v)
        elif isinstance(v, dict):
            v = dict(v)
        elif isinstance(v, set):
            v = set(v)
        out[k] = v
    return out


def _rag_cycle(edges: Iterable[Tuple[str, str]]) -> Optional[List[str]]:
    """First cycle in a waits-for digraph, as the node sequence
    ``[a, b, ..., a]`` — or None."""
    adj: Dict[str, List[str]] = {}
    for u, v in edges:
        adj.setdefault(u, []).append(v)
    color: Dict[str, int] = {}            # 0 absent / 1 on stack / 2 done
    stack: List[str] = []

    def dfs(u: str) -> Optional[List[str]]:
        color[u] = 1
        stack.append(u)
        for v in adj.get(u, ()):
            c = color.get(v, 0)
            if c == 1:
                return stack[stack.index(v):] + [v]
            if c == 0:
                cyc = dfs(v)
                if cyc is not None:
                    return cyc
        stack.pop()
        color[u] = 2
        return None

    for node in list(adj):
        if color.get(node, 0) == 0:
            cyc = dfs(node)
            if cyc is not None:
                return cyc
    return None


def _sccs(nodes: Set[int],
          edges: Sequence[Tuple[int, str, int]]) -> List[List[int]]:
    """Strongly connected components (iterative Tarjan) of the subgraph
    on ``nodes`` with the given labeled edges."""
    adj: Dict[int, List[int]] = {n: [] for n in nodes}
    for u, _a, v in edges:
        adj[u].append(v)
    index: Dict[int, int] = {}
    low: Dict[int, int] = {}
    on: Set[int] = set()
    stack: List[int] = []
    out: List[List[int]] = []
    counter = [0]
    for root in nodes:
        if root in index:
            continue
        work: List[Tuple[int, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on.add(node)
            advanced = False
            children = adj[node]
            while pi < len(children):
                child = children[pi]
                pi += 1
                if child not in index:
                    work[-1] = (node, pi)
                    work.append((child, 0))
                    advanced = True
                    break
                if child in on:
                    low[node] = min(low[node], index[child])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return out


class _Search:
    """One BFS exploration: states, parent pointers (shortest traces),
    and the labeled transition relation for liveness analysis."""

    def __init__(self, model: ProtoModel, bound: int):
        self.model = model
        self.bound = max(int(bound), 1)
        self.states: List[State] = []
        self.index: Dict[Any, int] = {}
        self.parent: List[Optional[Tuple[int, str]]] = []
        self.edges: List[Tuple[int, str, int]] = []
        self.truncated = False

    def intern(self, s: State) -> Tuple[Optional[int], bool]:
        """(index, is_new) — index is None when the state bound is hit."""
        key = _freeze(s)
        idx = self.index.get(key)
        if idx is not None:
            return idx, False
        if len(self.states) >= self.bound:
            self.truncated = True
            return None, False
        idx = len(self.states)
        self.index[key] = idx
        self.states.append(s)
        self.parent.append(None)
        return idx, True

    def trace_to(self, idx: int,
                 tail: Optional[Sequence[str]] = None) -> List[str]:
        """Rendered shortest path init -> states[idx] (+ optional tail
        lines, e.g. the lasso cycle of a starvation witness)."""
        hops: List[Tuple[str, int]] = []
        cur: Optional[int] = idx
        while cur is not None:
            link = self.parent[cur]
            if link is None:
                break
            pidx, action = link
            hops.append((action, cur))
            cur = pidx
        hops.reverse()
        rs = self.model.render_state
        lines = [f"init: {rs(self.states[cur])}"]
        for action, sidx in hops:
            lines.append(f"-> {action}: {rs(self.states[sidx])}")
        if tail:
            lines.extend(tail)
        return lines


def check(model: ProtoModel, bound: int = 20000,
          check_liveness: bool = True) -> ProtoReport:
    """Exhaustively explore ``model`` up to ``bound`` states and return
    a :class:`ProtoReport`.  One finding per rule (the BFS order makes
    it a shortest counterexample); exploration continues after a
    violation so one run surfaces every violated property."""
    t0 = time.perf_counter()
    report = ProtoReport(model=model.name)
    search = _Search(model, bound)

    inits = model.init()
    if isinstance(inits, dict):
        inits = [inits]
    queue: deque = deque()
    for s in inits:
        idx, fresh = search.intern(s)
        if idx is not None and fresh:
            queue.append(idx)

    seen_rules: Set[str] = set()

    def add(rule: str, severity: str, message: str, idx: int,
            tail: Optional[Sequence[str]] = None) -> None:
        if rule in seen_rules:
            return
        seen_rules.add(rule)
        report.findings.append(ProtoFinding(
            rule=rule, severity=severity, model=model.name,
            message=message, trace=search.trace_to(idx, tail)))

    while queue:
        idx = queue.popleft()
        s = search.states[idx]

        for inv_name, pred in model.invariants:
            if not pred(s):
                add(f"invariant:{inv_name}", ERROR,
                    f"reachable state violates invariant {inv_name!r}",
                    idx)

        if model.waits_for is not None:
            cyc = _rag_cycle(model.waits_for(s))
            if cyc is not None:
                add("circular-wait", ERROR,
                    "cycle in the resource-allocation graph: "
                    + " -> ".join(cyc), idx)

        is_terminal = bool(model.terminal(s)) if model.terminal else False
        if is_terminal:
            for inv_name, pred in model.terminal_invariants:
                if not pred(s):
                    add(f"terminal-invariant:{inv_name}", ERROR,
                        f"quiesced state violates {inv_name!r}", idx)

        n_enabled = 0
        for action in model.actions:
            if not action.guard(s):
                continue
            n_enabled += 1
            succ = action.effect(_copy_state(s))
            succs = succ if isinstance(succ, list) else [succ]
            for ns in succs:
                j, fresh = search.intern(ns)
                if j is None:
                    continue
                report.transitions += 1
                if fresh:
                    search.parent[j] = (idx, action.name)
                    queue.append(j)
                search.edges.append((idx, action.name, j))

        if n_enabled == 0 and not is_terminal:
            add("deadlock", ERROR,
                "reachable non-terminal state has no enabled action",
                idx)

    report.states = len(search.states)
    report.truncated = search.truncated

    if check_liveness and model.liveness and not search.truncated:
        _check_liveness(model, search, add)
    report.liveness_checked = (check_liveness and
                               not search.truncated)

    report.elapsed_s = time.perf_counter() - t0
    return report


def _check_liveness(model: ProtoModel, search: _Search,
                    add: Callable[..., None]) -> None:
    """Fair-lasso starvation search: SCCs of the pending subgraph with
    progress edges removed; a component survives weak fairness only if
    every fair action enabled at ALL of its states also fires inside
    it (otherwise fairness forces an escape)."""
    for spec in model.liveness:
        pend = {i for i, s in enumerate(search.states)
                if spec.pending(s)}
        sub = [(u, a, v) for (u, a, v) in search.edges
               if u in pend and v in pend and a not in spec.progress]
        for comp in _sccs(pend, sub):
            comp_set = set(comp)
            internal = [(u, a, v) for (u, a, v) in sub
                        if u in comp_set and v in comp_set]
            if not internal:
                continue                       # trivial SCC, no cycle
            labels = {a for (_u, a, _v) in internal}
            fair_escape = False
            for action in model.actions:
                if not action.fair or action.name in labels:
                    continue
                if all(action.guard(search.states[i]) for i in comp):
                    fair_escape = True         # fairness forces it out
                    break
            if fair_escape:
                continue
            entry = min(comp)
            tail = _lasso_tail(model, search, entry, comp_set, internal)
            add(f"starvation:{spec.name}", ERROR,
                f"fair cycle keeps {spec.name!r} pending while no "
                f"progress action ({', '.join(sorted(spec.progress))}) "
                f"ever fires", entry, tail)
            break


def _lasso_tail(model: ProtoModel, search: _Search, entry: int,
                comp: Set[int],
                internal: Sequence[Tuple[int, str, int]]) -> List[str]:
    """Render one cycle through ``entry`` inside the SCC."""
    adj: Dict[int, List[Tuple[str, int]]] = {}
    for u, a, v in internal:
        adj.setdefault(u, []).append((a, v))
    prev: Dict[int, Tuple[int, str]] = {}
    dq: deque = deque([entry])
    seen = {entry}
    back: Optional[Tuple[int, str]] = None
    while dq and back is None:
        u = dq.popleft()
        for a, v in adj.get(u, ()):
            if v == entry:
                back = (u, a)
                break
            if v not in seen:
                seen.add(v)
                prev[v] = (u, a)
                dq.append(v)
    lines = ["cycle (repeats forever):"]
    if back is None:
        return lines                           # defensive; SCC has cycle
    hops: List[Tuple[str, int]] = []
    u, a = back
    hops.append((a, entry))
    while u != entry:
        pu, pa = prev[u]
        hops.append((pa, u))
        u = pu
    hops.reverse()
    rs = model.render_state
    for action, sidx in hops:
        lines.append(f"~> {action}: {rs(search.states[sidx])}")
    return lines
