"""Protocol models for analysis/protocheck.py — the four protocols the
runtime keeps breaking, plus the seeded "pre-fix" variants that MUST be
caught (the regression contract for the checker itself).

Each model is a bounded, faithful abstraction of the shipped code:

- :func:`admission_budget` — the serving admission window
  (``_PoolAdmission.admit``/``on_retire``, serving/runtime.py) against
  the KV page budget (``KVPagePool``, serving/kv.py).  The seeded
  ``release="end_of_run"`` variant is PR 15's open-loop bug: a client
  that releases pages only at end of run deadlocks admission against
  the budget — protocheck reports it both as a deadlock and as a
  circular wait in the resource-allocation graph.
- :func:`kv_lifecycle` — page refcount/COW/cancel lifecycle from
  serving/kv.py + spec.py.  The seeded ``release="immediate"`` variant
  is the spec write-back-after-free: cancelling a draft and releasing
  its branch pages before the draft pool drained lets the in-flight
  write-back land on a freed (possibly reallocated) page.
- :func:`wfq_lanes` — the per-pool decode/prefill cadence of
  sched/fair.py, checked against the EXACT :func:`~..sched.fair.
  lane_choice` the scheduler runs.  The seeded ``broken_starvation``
  variant is the pre-fix semantics (prefill served only when decode is
  idle) — a fair lasso starves the prefill lane forever.
- :func:`termdet_cancel` — idempotent termination detection +
  ``Taskpool.cancel``: force-termination fires exactly once, the
  scheduler's drop-drain decrements never push counters negative or
  re-fire it, and a cancelled pool cannot poison a later ``wait``.

The models are deliberately small (tens to a few thousand states at
tier-1 bounds): protocol bugs here are ordering bugs, and the SPIN
lesson is that tiny instances already contain the counterexample.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..sched.fair import lane_choice
from .protocheck import Action, Liveness, ProtoModel

# --------------------------------------------------------------------------
# (a) admission window + on_retire + backpressure parking vs the page budget
# --------------------------------------------------------------------------

#: request lifecycle states in the admission/budget model
_NEW, _PARKED, _ADMITTED, _RUNNING, _DONE, _REJECTED = (
    "new", "parked", "admitted", "running", "done", "rejected")
_SETTLED = (_DONE, _REJECTED)


def admission_budget(n_requests: int = 3, window: int = 2, soft: int = 1,
                     pages: int = 2, per_req: int = 1,
                     release: str = "on_retire") -> ProtoModel:
    """Admission window/backpressure vs the KV page budget.

    ``release="on_retire"`` is the shipped protocol: a request's pages
    return to the budget when it retires.  ``release="end_of_run"`` is
    the PR 15 open-loop bug: every page is held until ALL requests have
    settled — the budget drains, later requests wait on pages held by
    finished requests whose release waits on the later requests.

    Timeouts (``serving.backpressure_timeout_s``) are deliberately NOT
    modeled: they mask the hang as rejection storms, they do not fix
    the protocol — the model checks the protocol.
    """
    n, w = int(n_requests), int(window)

    def init():
        return {"req": [_NEW] * n, "inflight": 0,
                "free": int(pages), "held": [0] * n}

    actions: List[Action] = []

    def mk(i: int) -> None:
        # admit: inflight at/below the soft threshold admits at once
        # (_PoolAdmission.admit keys backpressure on the EXISTING depth)
        actions.append(Action(
            f"admit(r{i})",
            lambda s, i=i: s["req"][i] == _NEW and s["inflight"] <= soft,
            lambda s, i=i: _set(s, i, _ADMITTED, dinflight=1)))
        # soft window: backpressure park (bounded by the hard window)
        actions.append(Action(
            f"park(r{i})",
            lambda s, i=i: (s["req"][i] == _NEW and s["inflight"] > soft
                            and s["inflight"] + 1 <= w),
            lambda s, i=i: _set(s, i, _PARKED)))
        # hard window: explicit rejection, never unbounded parking
        actions.append(Action(
            f"reject(r{i})",
            lambda s, i=i: (s["req"][i] == _NEW
                            and s["inflight"] + 1 > w),
            lambda s, i=i: _set(s, i, _REJECTED)))
        # on_retire notifies parked waiters; they recheck the soft gate
        actions.append(Action(
            f"unpark(r{i})",
            lambda s, i=i: (s["req"][i] == _PARKED
                            and s["inflight"] <= soft),
            lambda s, i=i: _set(s, i, _ADMITTED, dinflight=1),
            fair=True))
        # KV page allocation out of the shared budget
        actions.append(Action(
            f"alloc(r{i})",
            lambda s, i=i: (s["req"][i] == _ADMITTED
                            and s["free"] >= per_req),
            lambda s, i=i: _alloc(s, i, per_req)))
        # completion retires the admission rows (on_retire) and — in
        # the shipped protocol — returns the pages to the budget
        actions.append(Action(
            f"finish(r{i})",
            lambda s, i=i: s["req"][i] == _RUNNING,
            lambda s, i=i: _finish(s, i, release),
            fair=True))

    for i in range(n):
        mk(i)

    if release == "end_of_run":
        actions.append(Action(
            "end_of_run_release",
            lambda s: (all(r in _SETTLED for r in s["req"])
                       and sum(s["held"]) > 0),
            _end_run_release))

    def waits_for(s) -> List[Tuple[str, str]]:
        edges = []
        starved = s["free"] < per_req
        holders = [j for j in range(n) if s["held"][j] > 0]
        for i in range(n):
            if s["req"][i] == _ADMITTED and starved:
                for j in holders:
                    edges.append((f"r{i}", f"r{j}"))
        if release == "end_of_run":
            # a holder's pages are released by end-of-run, which waits
            # on every request that has not yet settled
            for j in holders:
                for k in range(n):
                    if s["req"][k] not in _SETTLED and k != j:
                        edges.append((f"r{j}", f"r{k}"))
        return edges

    return ProtoModel(
        name=f"admission_budget[{release}]",
        init=init,
        actions=actions,
        invariants=[
            ("page-budget-conserved",
             lambda s: s["free"] + sum(s["held"]) == pages),
            ("budget-nonnegative", lambda s: s["free"] >= 0),
            ("window-respected",
             lambda s: 0 <= s["inflight"] <= w),
        ],
        terminal=lambda s: (all(r in _SETTLED for r in s["req"])
                            and sum(s["held"]) == 0),
        terminal_invariants=[
            ("no-page-leak", lambda s: s["free"] == pages),
            ("window-drained", lambda s: s["inflight"] == 0),
        ],
        waits_for=waits_for,
        render=lambda s: (f"req={'/'.join(s['req'])} "
                          f"inflight={s['inflight']} free={s['free']} "
                          f"held={s['held']}"),
    )


def _set(s, i, st, dinflight=0):
    s["req"][i] = st
    s["inflight"] += dinflight
    return s


def _alloc(s, i, per_req):
    s["free"] -= per_req
    s["held"][i] += per_req
    s["req"][i] = _RUNNING
    return s


def _finish(s, i, release):
    s["req"][i] = _DONE
    s["inflight"] -= 1                      # on_retire
    if release == "on_retire":
        s["free"] += s["held"][i]
        s["held"][i] = 0
    return s


def _end_run_release(s):
    s["free"] += sum(s["held"])
    s["held"] = [0] * len(s["held"])
    return s


# --------------------------------------------------------------------------
# (b) KV page refcount / COW / cancel lifecycle (serving/kv.py + spec.py)
# --------------------------------------------------------------------------

def kv_lifecycle(release: str = "after_drain") -> ProtoModel:
    """Base request + one speculative branch over a 3-page pool.

    The branch COWs the base tail page and retains the shared prefix;
    the draft pool writes back into its branch page asynchronously.
    ``release="after_drain"`` is the shipped ``SpecController.release``
    protocol: branch pages are released only after the draft pool has
    drained.  ``release="immediate"`` is the seeded pre-fix bug:
    cancel releases the pages while a write-back is still in flight —
    it lands on a freed (and possibly reallocated) page.

    Pages: pid 0 = base prefix/tail, pids 1..2 free at init.  State
    tracks per-pid refcounts and owners, the draft pool phase, and a
    ``poison`` flag set when a write-back lands on a page the branch
    no longer owns — the write-back-after-free invariant.
    """
    npages = 3

    def init():
        return {"refs": [1, 0, 0],          # pid -> refcount (0 = free)
                "owner": ["base", None, None],
                "base": "running",
                "draft": "idle",            # idle/running/pending/done
                "branch": None,             # branch tail pid
                "cancelling": False,
                "poison": None}

    def free_pid(s):
        for pid in range(npages):
            if s["refs"][pid] == 0:
                return pid
        return None

    def spawn(s):
        pid = free_pid(s)
        s["refs"][pid] = 1                  # COW copy of the base tail
        s["owner"][pid] = "branch"
        s["refs"][0] += 1                   # branch retains the prefix
        s["branch"] = pid
        s["draft"] = "running"
        return s

    def land(s):
        pid = s["branch"]
        if s["owner"][pid] != "branch" or s["refs"][pid] <= 0:
            s["poison"] = pid               # write-back hit a dead page
        s["draft"] = "running"
        return s

    def release_branch(s):
        pid = s["branch"]
        if s["refs"][pid] > 0:
            s["refs"][pid] -= 1
        if s["refs"][pid] == 0:
            s["owner"][pid] = None
        s["refs"][0] -= 1                   # drop the prefix retain
        s["branch"] = None
        s["cancelling"] = False
        return s

    actions = [
        Action("spawn_branch",
               lambda s: (s["draft"] == "idle" and s["base"] == "running"
                          and s["branch"] is None
                          and free_pid(s) is not None),
               spawn),
        # the draft issues an async write-back aimed at its branch page
        Action("draft_write",
               lambda s: s["draft"] == "running" and s["branch"] is not None,
               lambda s: _setk(s, draft="pending")),
        # ... which lands later, after arbitrary interleavings
        Action("writeback_lands",
               lambda s: s["draft"] == "pending",
               land, fair=True),
    ]

    if release == "after_drain":
        # shipped protocol: cancel only MARKS; pages released after the
        # draft pool drained (SpecController.release waits on the pool)
        actions += [
            Action("cancel_branch",
                   lambda s: (s["branch"] is not None
                              and not s["cancelling"]
                              and s["draft"] in ("running", "pending")),
                   lambda s: _setk(s, cancelling=True)),
            Action("draft_drained",
                   lambda s: s["cancelling"] and s["draft"] == "running",
                   lambda s: _setk(s, draft="done"), fair=True),
            Action("release_after_drain",
                   lambda s: s["cancelling"] and s["draft"] == "done",
                   release_branch, fair=True),
        ]
    else:
        # seeded pre-fix bug: release the branch pages NOW, with the
        # write-back still in flight
        actions.append(Action(
            "cancel_release_immediate",
            lambda s: (s["branch"] is not None
                       and s["owner"][s["branch"]] == "branch"
                       and s["draft"] in ("running", "pending")),
            lambda s: release_branch_keep_tail(s)))

        def release_branch_keep_tail(s):
            # same page release, but the draft still targets the pid
            pid = s["branch"]
            if s["refs"][pid] > 0:
                s["refs"][pid] -= 1
            if s["refs"][pid] == 0:
                s["owner"][pid] = None
            s["refs"][0] -= 1
            s["cancelling"] = False
            # branch pid kept: the in-flight write-back still aims here
            return s

        # a freed page is immediately reusable by another request —
        # making the landing write a cross-request corruption
        def realloc(s):
            for pid in range(1, npages):
                if s["refs"][pid] == 0 and s["owner"][pid] is None:
                    s["refs"][pid] = 1
                    s["owner"][pid] = "other"
                    break
            return s

        actions.append(Action(
            "realloc_freed_page",
            lambda s: any(s["refs"][p] == 0 for p in range(1, npages))
            and s["branch"] is not None and s["owner"][s["branch"]] is None,
            realloc))

    def branch_resolved(s):
        if release == "after_drain":
            return s["branch"] is None
        # seeded variant: branch pid is kept for the in-flight write;
        # resolved once the draft has no write pending
        return s["branch"] is None or (s["owner"][s["branch"]] != "branch"
                                       and s["draft"] != "pending")

    actions.append(Action(
        "base_finish",
        lambda s: (s["base"] == "running" and branch_resolved(s)
                   and s["draft"] in ("idle", "done", "running")
                   and not s["cancelling"]),
        lambda s: _base_finish(s)))

    return ProtoModel(
        name=f"kv_lifecycle[{release}]",
        init=init,
        actions=actions,
        invariants=[
            ("no-write-after-free", lambda s: s["poison"] is None),
            ("refs-nonnegative",
             lambda s: all(r >= 0 for r in s["refs"])),
            ("free-has-no-owner",
             lambda s: all((r > 0) == (o is not None)
                           for r, o in zip(s["refs"], s["owner"]))),
        ],
        terminal=lambda s: (s["base"] == "released"
                            and s["branch"] is None
                            and s["draft"] in ("idle", "done")),
        terminal_invariants=[
            ("pages-in-use-zero", lambda s: sum(s["refs"]) == 0),
        ],
        render=lambda s: (f"refs={s['refs']} owner={s['owner']} "
                          f"base={s['base']} draft={s['draft']} "
                          f"branch={s['branch']} "
                          f"cancelling={s['cancelling']} "
                          f"poison={s['poison']}"),
    )


def _setk(s, **kw):
    s.update(kw)
    return s


def _base_finish(s):
    s["refs"][0] -= 1
    if s["refs"][0] == 0:
        s["owner"][0] = None
    s["base"] = "released"
    return s


# --------------------------------------------------------------------------
# (c) wfq decode/prefill lane cadence (sched/fair.py)
# --------------------------------------------------------------------------

def _broken_lane_choice(ndq: int, npq: int, nsel: int,
                        interleave: int) -> str:
    """Pre-fix semantics: prefill served only when decode is idle —
    an open-loop decode arrival stream starves prefill forever."""
    return "prefill" if not ndq else "decode"


def wfq_lanes(interleave: int = 4, dmax: int = 2, pmax: int = 2,
              choice=lane_choice) -> ProtoModel:
    """One wfq pool's two lanes under adversarial (unfair) arrivals.

    The serve actions are mutually exclusive and deterministic given
    the state — the guard IS :func:`parsec_tpu.sched.fair.lane_choice`,
    the function ``WFQScheduler.select`` executes, so the model cannot
    drift from the implementation.  Serves are weakly fair (the worker
    loop runs whenever work is queued); arrivals are not (the client
    owes the runtime nothing).  Starvation-freedom of BOTH lanes is
    the property; ``nsel`` is tracked modulo the cadence.
    """
    cadence = max(int(interleave), 2)

    def init():
        return {"dq": 0, "pq": 0, "nsel": 0}

    def serve(s, lane):
        s["dq" if lane == "decode" else "pq"] -= 1
        s["nsel"] = (s["nsel"] + 1) % cadence
        return s

    actions = [
        Action("arrive_decode",
               lambda s: s["dq"] < dmax,
               lambda s: _setk(s, dq=s["dq"] + 1)),
        Action("arrive_prefill",
               lambda s: s["pq"] < pmax,
               lambda s: _setk(s, pq=s["pq"] + 1)),
        Action("serve_decode",
               lambda s: (s["dq"] + s["pq"] > 0 and
                          choice(s["dq"], s["pq"], s["nsel"] + 1,
                                 interleave) == "decode"),
               lambda s: serve(s, "decode"), fair=True),
        Action("serve_prefill",
               lambda s: (s["dq"] + s["pq"] > 0 and
                          choice(s["dq"], s["pq"], s["nsel"] + 1,
                                 interleave) == "prefill"),
               lambda s: serve(s, "prefill"), fair=True),
    ]

    return ProtoModel(
        name=f"wfq_lanes[interleave={interleave}]",
        init=init,
        actions=actions,
        invariants=[
            ("lanes-nonnegative",
             lambda s: s["dq"] >= 0 and s["pq"] >= 0),
        ],
        # no terminal: an idle pool always accepts arrivals
        liveness=[
            Liveness("prefill-lane", lambda s: s["pq"] > 0,
                     frozenset({"serve_prefill"})),
            Liveness("decode-lane", lambda s: s["dq"] > 0,
                     frozenset({"serve_decode"})),
        ],
        render=lambda s: (f"dq={s['dq']} pq={s['pq']} "
                          f"nsel%{cadence}={s['nsel']}"),
    )


# --------------------------------------------------------------------------
# (d) idempotent termdet + Taskpool.cancel vs a later wait
# --------------------------------------------------------------------------

def termdet_cancel(n_tasks: int = 2) -> ProtoModel:
    """Pool A is cancelled mid-flight while pool B runs normally; a
    context waiter waits on both.  The idempotent-termination contract:
    force-termination on cancel fires termdet exactly once, the
    scheduler's drop-drain decrements (``_drop_cancelled_locked``)
    reconcile the task counter without re-firing it or driving it
    negative, and the waiter completes — a cancelled pool can neither
    hang nor poison a later ``wait``.
    """
    n = int(n_tasks)

    def init():
        return {"nA": n, "qA": n, "cancelledA": False, "termA": 0,
                "nB": 1, "qB": 1, "termB": 0,
                "waiter": "waiting"}

    def run_a(s):
        s["qA"] -= 1
        s["nA"] -= 1
        if s["nA"] == 0 and s["termA"] == 0:
            s["termA"] = 1
        return s

    def cancel_a(s):
        s["cancelledA"] = True
        if s["termA"] == 0:                  # force-terminate, once
            s["termA"] = 1
        return s

    def drop_a(s):
        # idempotent contract: drain the counter, never re-terminate
        s["qA"] -= 1
        s["nA"] -= 1
        if s["nA"] == 0 and s["termA"] == 0:
            s["termA"] = 1
        return s

    def run_b(s):
        s["qB"] -= 1
        s["nB"] -= 1
        if s["nB"] == 0 and s["termB"] == 0:
            s["termB"] = 1
        return s

    actions = [
        Action("run_A",
               lambda s: s["qA"] > 0 and not s["cancelledA"],
               run_a, fair=True),
        Action("cancel_A",
               lambda s: not s["cancelledA"],
               cancel_a),
        Action("drop_A",
               lambda s: s["cancelledA"] and s["qA"] > 0,
               drop_a, fair=True),
        Action("run_B",
               lambda s: s["qB"] > 0,
               run_b, fair=True),
        Action("wait_returns",
               lambda s: (s["waiter"] == "waiting" and s["termA"] >= 1
                          and s["termB"] >= 1 and s["qA"] == 0
                          and s["qB"] == 0),
               lambda s: _setk(s, waiter="done"), fair=True),
    ]

    return ProtoModel(
        name="termdet_cancel",
        init=init,
        actions=actions,
        invariants=[
            ("counters-nonnegative",
             lambda s: s["nA"] >= 0 and s["qA"] >= 0 and s["nB"] >= 0),
            ("termdet-idempotent",
             lambda s: s["termA"] <= 1 and s["termB"] <= 1),
        ],
        terminal=lambda s: s["waiter"] == "done",
        terminal_invariants=[
            ("pools-reconciled",
             lambda s: s["nA"] == 0 and s["nB"] == 0),
            ("termdet-fired-once",
             lambda s: s["termA"] == 1 and s["termB"] == 1),
        ],
        render=lambda s: (f"A(n={s['nA']} q={s['qA']} "
                          f"cancelled={s['cancelledA']} term={s['termA']}) "
                          f"B(n={s['nB']} term={s['termB']}) "
                          f"waiter={s['waiter']}"),
    )


# --------------------------------------------------------------------------
# registries
# --------------------------------------------------------------------------

#: current-protocol models — the zero-violation contract at tier-1 bounds
MODELS: Dict[str, object] = {
    "admission": admission_budget,
    "kv_lifecycle": kv_lifecycle,
    "wfq_lanes": wfq_lanes,
    "termdet": termdet_cancel,
}

#: seeded pre-fix variants -> (factory, rule prefix protocheck MUST report)
SEEDED: Dict[str, Tuple[object, str]] = {
    "budget_deadlock": (
        lambda: admission_budget(release="end_of_run"), "deadlock"),
    "budget_circular_wait": (
        lambda: admission_budget(release="end_of_run"), "circular-wait"),
    "spec_writeback_after_free": (
        lambda: kv_lifecycle(release="immediate"),
        "invariant:no-write-after-free"),
    "prefill_starvation": (
        lambda: wfq_lanes(interleave=1, choice=_broken_lane_choice),
        "starvation:prefill-lane"),
}


def build(name: str, **kw) -> ProtoModel:
    """Instantiate a registered current-protocol model by name."""
    if name not in MODELS:
        raise KeyError(f"unknown protocol model {name!r}; have "
                       f"{', '.join(sorted(MODELS))}")
    return MODELS[name](**kw)
