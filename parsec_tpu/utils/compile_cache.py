"""Compile-once serving: persistent executor cache + shared jit store.

PaRSEC's core compile economy is that a *task class* is compiled once
(JDF -> parsec_ptgpp at build time) and instances are nearly free; our
compiled executors re-lowered per (N, taskpool) and paid a multi-second
XLA stall on every new problem size — the PARITY compile-time-scaling
table shows 20-70 s warm for the panel-fused flagship, minutes for
whole-DAG jit at NT=32. This module restores the once-per-program
economics with three layers:

1. **In-process shared jit store** (:func:`cached_jit`): jitted
   callables keyed by a *semantic* key — body code fingerprints, tile
   geometry, bucket shape, trace-affecting MCA knobs — instead of by
   function object. Rebuilding an executor (or a whole Context) for an
   already-served bucket never re-traces (``jax.jit`` caches by function
   identity, so every fresh wrapper used to pay a full re-trace).
2. **Persistent executor store** (:class:`ExecutorStore`): AOT
   ``lower() -> compile() -> serialize_executable`` keyed by a
   :func:`lowering_fingerprint` covering the parsec_tpu version salt,
   jax/jaxlib versions, device kind/count, and the caller's key parts
   (NB, dtype, bucket shape, body hooks, mesh/sharding). A cache hit
   skips tracing AND lowering AND XLA — the second *process* to serve a
   bucket pays only deserialization. (The XLA persistent cache, by
   contrast, must re-trace and re-lower the whole program just to
   compute its key — that IS the 20-70 s "warm" cost.)
3. The classic **XLA persistent compilation cache** toggle
   (:func:`enable_compile_cache`), kept as the safety net for programs
   that bypass the store.

Env/knob interaction (documented contract):

- ``jit.cache_dir`` MCA knob (env ``PARSEC_MCA_jit_cache_dir``):
  ``""`` = disabled (library default), ``auto`` = ``.xla_cache`` next
  to the repo root, anything else = that directory. bench.py and the
  compiled-path examples set it to ``auto`` — serving entry points opt
  in; the library never writes caches unasked.
- ``PARSEC_COMPILE_CACHE`` env: legacy/kill switch. ``0`` disables BOTH
  layers even when the knob is set; a path overrides the knob's
  directory. :func:`enable_compile_cache` remains the explicit call.
- ``jit.cache_salt`` MCA knob: extra fingerprint salt — flip it to
  force a cold cache without deleting files (tests use this for the
  version-salt invalidation contract).

Cache layout under ``<dir>/``: XLA's own cache files at the top level
(unchanged), serialized executables under ``executors/<digest>.pkl``
(pickle of {schema, key, payload, in_tree, out_tree}; the digest is the
sha256 lowering fingerprint, so key checks are pure file existence).
The store is a local trust domain (pickle), like the XLA cache itself.
"""

from __future__ import annotations

import functools
import hashlib
import os
import pickle
import threading
import types
from typing import Any, Callable, Dict, Optional, Tuple

from . import mca_param
from .debug import debug_verbose, warning

_SCHEMA = 1

mca_param.register(
    "jit.cache_dir", "",
    help="persistent compile-cache directory for the compiled executors "
         "('' = disabled, 'auto' = .xla_cache next to the repo root, "
         "else a path). Enables BOTH the XLA persistent cache and the "
         "serialized-executor store; PARSEC_COMPILE_CACHE=0 is the "
         "kill switch that overrides this knob")
mca_param.register(
    "jit.cache_salt", "",
    help="extra salt mixed into every lowering fingerprint; flip to "
         "invalidate the executor store without deleting files")
mca_param.register(
    "jit.persist_executors", 1,
    help="serialize AOT-compiled executables into the cache dir "
         "(0 = in-process jit sharing only)")


# ---------------------------------------------------------------------------
# trace-affecting MCA knobs
# ---------------------------------------------------------------------------
# Compiled bodies and wave fusers read MCA parameters at TRACE time
# (potrf.trsm_hook picks the TRSM kernel, ops.matmul_precision the MXU
# pass count, ...). Two traces of the same function under different
# knob values produce different programs, so every shared-cache key
# must include the resolved values — components register the knobs
# whose values their traced code depends on, and the fingerprint
# snapshots all of them. Over-invalidation (a knob flip missing caches
# that never read it) is accepted: correctness over hit rate.

_TRACE_KNOBS: set = set()
_TK_LOCK = threading.Lock()


def register_trace_knob(name: str) -> None:
    """Declare ``name`` as an MCA param whose value affects traced
    programs; its resolved value enters every lowering fingerprint."""
    with _TK_LOCK:
        _TRACE_KNOBS.add(name)


def trace_knob_snapshot() -> Tuple[Tuple[str, Any], ...]:
    with _TK_LOCK:
        names = sorted(_TRACE_KNOBS)
    return tuple((n, mca_param.get(n)) for n in names)


# ---------------------------------------------------------------------------
# compilation counters (jax.monitoring)
# ---------------------------------------------------------------------------
# '/jax/core/compile/backend_compile_duration' fires once per actual
# XLA backend compile (persistent-cache hits do NOT fire it) — the
# counter the compile-once tests assert on instead of wall clock.

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_counters = {"backend_compiles": 0, "store_hits": 0, "store_misses": 0,
             "store_errors": 0, "jit_store_hits": 0}
_listener_installed = False
_CNT_LOCK = threading.Lock()


def _install_listener() -> None:
    global _listener_installed
    with _CNT_LOCK:
        if _listener_installed:
            return
        _listener_installed = True
    from jax import monitoring

    def _on_duration(event, duration, **kwargs):  # noqa: ARG001
        if event == _BACKEND_COMPILE_EVENT:
            with _CNT_LOCK:
                _counters["backend_compiles"] += 1

    monitoring.register_event_duration_secs_listener(_on_duration)


def backend_compile_count() -> int:
    """Process-wide count of actual XLA backend compiles since the
    counter was first consulted (monitoring listener installed lazily —
    call once BEFORE the region you want counted)."""
    _install_listener()
    with _CNT_LOCK:
        return _counters["backend_compiles"]


def cache_stats() -> Dict[str, int]:
    with _CNT_LOCK:
        return dict(_counters)


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------

def function_fingerprint(fn: Callable) -> Tuple[bool, str]:
    """``(stable, digest)`` for a Python callable's *traced behavior*:
    code objects (recursively through nested consts and closure
    functions), module/qualname, default args, and closure-cell
    literals. ``stable=True`` means the digest is reproducible across
    processes (safe to persist / share across equal rebuilds);
    ``stable=False`` means some ingredient (an unhashable closure cell,
    a bound method of a stateful object) fell back to ``id()`` — valid
    only per-process AND only while the caller keeps the object alive,
    so unstable fingerprints must stay in per-instance caches.

    Deliberately NOT covered: the code of *global* functions the body
    calls by name (only the name is hashed) — repo-level changes are
    covered by the parsec version salt in :func:`lowering_fingerprint`,
    and runtime-variant behavior must go through registered trace
    knobs."""
    import numpy as np

    parts = []
    stable = [True]
    seen = set()

    def code(c: types.CodeType) -> None:
        parts.append(hashlib.sha256(c.co_code).hexdigest()[:16])
        parts.append(str(c.co_names))
        parts.append(str(c.co_varnames))
        for const in c.co_consts:
            if isinstance(const, types.CodeType):
                code(const)
            else:
                lit(const, 0)

    def lit(v: Any, depth: int) -> None:
        if depth > 8:
            stable[0] = False
            parts.append("depth")
            return
        if v is None or isinstance(v, (bool, int, float, str, bytes,
                                       complex)):
            parts.append(repr(v))
        elif isinstance(v, (tuple, frozenset)):
            parts.append("(")
            for x in (sorted(v, key=repr) if isinstance(v, frozenset)
                      else v):
                lit(x, depth + 1)
            parts.append(")")
        elif isinstance(v, np.dtype):
            parts.append(str(v))
        elif isinstance(v, types.FunctionType):
            walk(v, depth + 1)
        elif isinstance(v, types.CodeType):
            code(v)
        else:
            stable[0] = False
            parts.append(f"id:{id(v)}")

    def walk(f: Callable, depth: int) -> None:
        if id(f) in seen:       # cycles through closure cells
            parts.append("cycle")
            return
        seen.add(id(f))
        if isinstance(f, functools.partial):
            parts.append("partial")
            walk(f.func, depth + 1)
            for a in f.args:
                lit(a, depth + 1)
            for k in sorted(f.keywords or {}):
                parts.append(k)
                lit(f.keywords[k], depth + 1)
            return
        c = getattr(f, "__code__", None)
        if c is None:
            # builtins / callable objects: name-identified only
            parts.append(getattr(f, "__module__", "") or "")
            qn = getattr(f, "__qualname__", None)
            if qn is None:
                stable[0] = False
                parts.append(f"obj:{id(f)}")
            else:
                parts.append(qn)
            return
        parts.append(getattr(f, "__module__", "") or "")
        parts.append(getattr(f, "__qualname__", c.co_name))
        code(c)
        for cell in getattr(f, "__closure__", None) or ():
            try:
                lit(cell.cell_contents, depth + 1)
            except ValueError:       # empty cell
                parts.append("emptycell")
        for d in getattr(f, "__defaults__", None) or ():
            lit(d, depth + 1)

    walk(fn, 0)
    digest = hashlib.sha256("\x00".join(parts).encode()).hexdigest()
    return stable[0], digest


def _canon(part: Any, out: list) -> None:
    """Canonicalize one key part into hashable strings (handles
    ShapeDtypeStructs, dtypes, arrays-as-shapes, nested containers)."""
    import numpy as np
    if part is None or isinstance(part, (bool, int, float, str, bytes,
                                         complex)):
        out.append(repr(part))
    elif isinstance(part, (tuple, list)):
        out.append("(")
        for p in part:
            _canon(p, out)
        out.append(")")
    elif isinstance(part, dict):
        out.append("{")
        for k in sorted(part, key=repr):
            out.append(repr(k))
            _canon(part[k], out)
        out.append("}")
    elif isinstance(part, np.dtype):
        out.append(str(part))
    elif hasattr(part, "shape") and hasattr(part, "dtype"):
        out.append(f"sds{tuple(part.shape)}:{np.dtype(part.dtype)}")
    elif isinstance(part, types.FunctionType):
        out.append(function_fingerprint(part)[1])
    else:
        out.append(repr(part))


def _device_signature() -> Tuple:
    import jax
    devs = jax.devices()
    d = devs[0]
    return (d.platform, getattr(d, "device_kind", "?"), len(devs))


def lowering_fingerprint(*key_parts: Any) -> str:
    """sha256 digest over the standard fingerprint fields + the
    caller's key parts. Standard fields: parsec_tpu version (+
    ``jit.cache_salt``), jax/jaxlib versions, backend device
    kind/count, and the registered trace-knob snapshot."""
    import jax
    import jaxlib
    from ..version import __version__
    out: list = [f"schema{_SCHEMA}", __version__,
                 str(mca_param.get("jit.cache_salt", "")),
                 jax.__version__, jaxlib.__version__,
                 repr(_device_signature())]
    _canon(trace_knob_snapshot(), out)
    for part in key_parts:
        _canon(part, out)
    return hashlib.sha256("\x00".join(out).encode()).hexdigest()


# ---------------------------------------------------------------------------
# persistent executor store
# ---------------------------------------------------------------------------

def _initialize_ffi_runtime() -> None:
    """Bind the CPU custom-call runtime before any deserialization.

    jaxlib's LAPACK custom-call stubs resolve their BLAS/LAPACK
    function pointers via ``_lapack.initialize()``, which jax invokes
    lazily from the LOWERING helpers. A warm serving process that only
    *deserializes* executables never lowers anything, so a loaded
    program containing a cholesky/triangular-solve custom call would
    dispatch through unbound pointers — measured as a hard segfault on
    the first such executable. Best-effort by design: absent modules
    (TPU-only jaxlib builds, future renames) just skip."""
    try:
        from jaxlib.cpu import _lapack
        _lapack.initialize()
    except Exception:  # noqa: BLE001 — registration is best-effort
        pass


class ExecutorStore:
    """Serialized-executable store: ``<root>/<digest>.pkl`` holding the
    AOT-compiled program. Writes are atomic (tmp + rename); any load
    failure (version skew, corruption, foreign device) degrades to a
    miss and the entry is recompiled + overwritten."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)
        _initialize_ffi_runtime()

    def _path(self, digest: str) -> str:
        return os.path.join(self.root, digest + ".pkl")

    def load(self, digest: str) -> Optional[Callable]:
        path = self._path(digest)
        if not os.path.exists(path):
            with _CNT_LOCK:
                _counters["store_misses"] += 1
            return None
        try:
            with open(path, "rb") as fh:
                rec = pickle.load(fh)
            if rec.get("schema") != _SCHEMA:
                raise ValueError(f"schema {rec.get('schema')}")
            from jax.experimental import serialize_executable as se
            fn = se.deserialize_and_load(
                rec["payload"], rec["in_tree"], rec["out_tree"])
            with _CNT_LOCK:
                _counters["store_hits"] += 1
            debug_verbose(3, "jitcache", "store hit %s (%s)",
                          digest[:12], rec.get("key", "?")[:80])
            return fn
        except Exception as exc:  # noqa: BLE001 — degrade to a miss
            with _CNT_LOCK:
                _counters["store_errors"] += 1
            debug_verbose(1, "jitcache", "store load %s failed: %s",
                          digest[:12], exc)
            return None

    def save(self, digest: str, compiled: Any, key_repr: str) -> None:
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(compiled)
            rec = {"schema": _SCHEMA, "key": key_repr,
                   "payload": payload, "in_tree": in_tree,
                   "out_tree": out_tree}
            tmp = self._path(digest) + f".tmp{os.getpid()}"
            with open(tmp, "wb") as fh:
                pickle.dump(rec, fh)
            os.replace(tmp, self._path(digest))
        except Exception as exc:  # noqa: BLE001 — cache is best-effort
            warning("jitcache", "store save %s failed: %s",
                    digest[:12], exc)


_store: Optional[ExecutorStore] = None
_store_checked = False
_store_gen = -1        # mca generation the negative check was made at
_STORE_LOCK = threading.Lock()


def _default_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".xla_cache")


def _resolve_dir(path: Optional[str] = None) -> Optional[str]:
    """Directory resolution shared by the explicit call and the knob
    auto-enable: PARSEC_COMPILE_CACHE=0 kills everything; explicit path
    > env path > jit.cache_dir knob ('auto' -> repo .xla_cache)."""
    env = os.environ.get("PARSEC_COMPILE_CACHE", "")
    if env == "0":
        return None
    if path is not None:
        return path
    if env:
        return env
    knob = str(mca_param.get("jit.cache_dir", "")).strip()
    if knob in ("", "0", "off"):
        return None
    return _default_dir() if knob == "auto" else knob


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache AND the serialized-
    executor store at ``path`` (default: ``$PARSEC_COMPILE_CACHE``, the
    ``jit.cache_dir`` MCA knob, or ``.xla_cache`` next to the repo
    root). Set ``PARSEC_COMPILE_CACHE=0`` to disable. Safe to call
    repeatedly; returns the cache dir in use (None when disabled)."""
    global _store, _store_checked
    env = os.environ.get("PARSEC_COMPILE_CACHE", "")
    if env == "0":
        with _STORE_LOCK:
            _store, _store_checked = None, True
        return None
    if path is None:
        path = env or _resolve_dir() or _default_dir()
    import jax
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except AttributeError:   # knob name varies across jax versions
        pass
    with _STORE_LOCK:
        if _store is None or _store.root != os.path.join(path, "executors"):
            _store = ExecutorStore(os.path.join(path, "executors"))
        _store_checked = True
    return path


def disable_compile_cache() -> None:
    """Drop the executor store (tests; the XLA cache dir config is left
    as-is — it is process state the caller owns)."""
    global _store, _store_checked
    with _STORE_LOCK:
        _store, _store_checked = None, False


def executor_store() -> Optional[ExecutorStore]:
    """The active store, auto-enabling from the ``jit.cache_dir`` knob
    on first use (the knob path — bench/examples — needs no manual
    :func:`enable_compile_cache` call). A negative answer is re-checked
    whenever the MCA registry changes, so setting the knob after a
    disabled lookup still enables the store."""
    global _store_checked, _store_gen
    gen = mca_param.generation()
    with _STORE_LOCK:
        if _store is not None or (_store_checked and _store_gen == gen):
            return _store
    d = _resolve_dir()
    if d is None:
        with _STORE_LOCK:
            _store_checked = True
            _store_gen = gen
        return None
    enable_compile_cache(d)
    return _store


# ---------------------------------------------------------------------------
# shared jit store
# ---------------------------------------------------------------------------

_JIT_STORE: Dict[str, Callable] = {}
_JIT_LOCK = threading.Lock()


def reset_in_process_cache() -> None:
    """Drop the in-process shared jit store (tests simulate a fresh
    process to exercise the persistent layer)."""
    with _JIT_LOCK:
        _JIT_STORE.clear()


def jit_store_size() -> int:
    with _JIT_LOCK:
        return len(_JIT_STORE)


def cached_jit(fn: Callable, *, key: Tuple, example_args: Tuple = None,
               donate_argnums=(), static_argnums=(),
               jit_wrapper: Callable = None,
               persist: bool = True) -> Callable:
    """The compiled path's jit entry point: a callable shared in-process
    by semantic ``key`` and (when the store is enabled and
    ``example_args`` abstract shapes are given) AOT-compiled +
    serialized under the :func:`lowering_fingerprint` of that key.

    - in-process hit: the existing callable, zero tracing.
    - store hit: deserialize, zero tracing/lowering/XLA.
    - miss with ``example_args``: ``jit(fn).lower(*args).compile()``
      EAGERLY (so warm-up passes like ``prepare_segments`` really
      resolve every compile up front), serialized for the next process
      when the store is enabled. The returned executable accepts
      exactly the example shapes — callers put every shape in the key.
    - miss without ``example_args``: a plain shared ``jax.jit`` wrapper
      (multi-shape; in-process sharing only).

    ``jit_wrapper`` overrides ``jax.jit`` construction (the pjit front
    end passes shardings through it). Keys MUST cover everything that
    changes the trace: the caller's code fingerprints, shapes/dtypes,
    bucket sizes — the standard fields (versions, device, trace knobs,
    salt) are added by :func:`lowering_fingerprint`.
    """
    digest = lowering_fingerprint(*key)
    with _JIT_LOCK:
        hit = _JIT_STORE.get(digest)
    if hit is not None:
        with _CNT_LOCK:
            _counters["jit_store_hits"] += 1
        return hit
    import jax
    if jit_wrapper is not None:
        jitted = jit_wrapper(fn)
    else:
        jitted = jax.jit(fn, donate_argnums=donate_argnums,
                         static_argnums=static_argnums)
    result = jitted
    store = executor_store() if (persist and int(
        mca_param.get("jit.persist_executors", 1))) else None
    if example_args is not None:
        loaded = store.load(digest) if store is not None else None
        if loaded is not None:
            result = loaded
        else:
            try:
                compiled = jitted.lower(*example_args).compile()
                if store is not None:
                    out: list = []
                    _canon(key, out)
                    store.save(digest, compiled, "|".join(out))
                result = compiled
            except Exception as exc:  # noqa: BLE001 — fall back to jit
                warning("jitcache", "AOT compile for %s failed (%s); "
                        "falling back to plain jit", digest[:12], exc)
                result = jitted
    with _JIT_LOCK:
        return _JIT_STORE.setdefault(digest, result)
