"""Persistent XLA compilation cache.

The flagship panel-fused programs compile in ~100-200 s through the
remote-tunnel backend; the persistent cache cuts warm re-compiles to
seconds (measured 170 s -> 40 s for the 94-wave GEQRF program, 7 s ->
2 s for small programs — the warm residue is cache deserialization).
Reference analog: the reference pays its codegen cost once at ptgpp
compile time; here the XLA binary is the generated artifact, so caching
it across processes restores the same once-per-program economics.
"""

from __future__ import annotations

import os


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``path`` (default:
    ``$PARSEC_COMPILE_CACHE`` or ``.xla_cache`` next to the repo root).
    Set ``PARSEC_COMPILE_CACHE=0`` to disable. Safe to call repeatedly;
    returns the cache dir in use (None when disabled)."""
    env = os.environ.get("PARSEC_COMPILE_CACHE", "")
    if env == "0":
        return None
    if path is None:
        path = env or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".xla_cache")
    import jax
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except AttributeError:   # knob name varies across jax versions
        pass
    return path
