from . import mca_param
from . import debug
from . import vpmap
from . import cmd_line
from .zone_malloc import ZoneAllocator
