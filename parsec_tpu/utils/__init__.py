from . import mca_param
from . import debug
