"""Command-line parsing for runtime options (reference
parsec/utils/cmd_line.c + the option table in parsec_init,
parsec.c:411-463).

Recognized options (each also settable as an MCA param):

    --mca <key> <value>     set any MCA parameter
    -c / --cores N          worker streams          (runtime.nb_cores)
    -V / --vpmap SPEC       virtual-process map     (vpmap)
    --sched NAME            scheduler module        (sched)
    --pins M1,M2            PINS modules            (pins)
    --dot FILE              DAG capture to FILE     (profiling.dot)
    -h / --help             return the help text instead of parsing on

``parse`` applies recognized options to the MCA registry and returns the
leftover argv (the reference hands those back to the application).
"""

from __future__ import annotations

from typing import List

from . import mca_param

_OPTIONS = {
    # flag           (mca name,          takes_value)
    "-c":            ("runtime.nb_cores", True),
    "--cores":       ("runtime.nb_cores", True),
    "-V":            ("vpmap", True),
    "--vpmap":       ("vpmap", True),
    "--sched":       ("sched", True),
    "--pins":        ("pins", True),
    "--dot":         ("profiling.dot", True),
}


class HelpRequested(Exception):
    """Raised by parse() on -h/--help; carries the help text."""

    def __init__(self, text: str):
        super().__init__(text)
        self.text = text


def help_text() -> str:
    """The --help dump: every registered MCA parameter with its current
    value (parsec.c:903-918 analog)."""
    lines = ["parsec_tpu runtime options:",
             "  --mca <key> <value>   set an MCA parameter", ""]
    for flag, (name, _) in sorted(_OPTIONS.items()):
        lines.append(f"  {flag:<22}-> {name}")
    lines.append("")
    lines.append("MCA parameters (name = value, default, help):")
    for row in mca_param.dump():
        lines.append(f"  {row['name']} = {row['value']!r} "
                     f"(default {row['default']!r}) — {row['help']}")
    return "\n".join(lines)


def parse(argv: List[str]) -> List[str]:
    """Apply recognized options to the MCA registry; return leftover argv.
    Raises :class:`HelpRequested` on ``-h``/``--help``."""
    argv = mca_param.parse_cli(list(argv))
    out: List[str] = []
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg == "--mca":
            # parse_cli only consumes complete triples; a surviving --mca
            # means the key or value is missing
            raise ValueError("--mca requires a key and a value")
        if arg in ("-h", "--help"):
            raise HelpRequested(help_text())
        opt = _OPTIONS.get(arg)
        if opt is None:
            out.append(arg)
            i += 1
            continue
        name, takes_value = opt
        if takes_value:
            if i + 1 >= len(argv):
                raise ValueError(f"{arg} requires a value")
            mca_param.set(name, argv[i + 1])
            i += 2
        else:
            mca_param.set(name, True)
            i += 1
    return out
