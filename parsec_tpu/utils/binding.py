"""Best-effort thread→core binding (parsec_hwloc.c / bindthread.c analog).

The reference binds worker threads to cores from the ``-b`` binding
specification (parsec_parse_binding_parameter, parsec.c:2313-2519) and
the comm thread to its own core (remote_dep.c:645,
remote_dep_bind_thread). Python threads share the GIL, but OS-level
affinity still matters for the comm thread (keeps it off the cores the
GIL-released native/XLA work runs on) and for NUMA locality of worker
stacks. No hwloc in this environment: Linux ``sched_setaffinity`` on the
calling thread (tid 0) is the whole mechanism, and every call is
best-effort — failure is recorded, never raised.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

from . import mca_param
from .debug import debug_verbose

mca_param.register("runtime.bind_workers", 0,
                   help="bind worker thread i to core binding[i % n] "
                        "(parsec -b analog; 0 = no binding)")
mca_param.register("runtime.binding_list", "",
                   help="comma-separated core list for worker binding "
                        "(empty = all cores in os order)")
mca_param.register("comm.bind_core", -1,
                   help="core to bind the comm thread to "
                        "(remote_dep_bind_thread analog; -1 = none)")


def available_cores() -> Sequence[int]:
    try:
        return sorted(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return []


def _binding_list() -> Sequence[int]:
    spec = str(mca_param.get("runtime.binding_list", "") or "")
    if spec:
        try:
            return [int(x) for x in spec.split(",") if x.strip() != ""]
        except ValueError:
            debug_verbose(1, "binding", "bad binding list %r", spec)
    return list(available_cores())


def bind_current_thread(core: int) -> bool:
    """Pin the calling thread to ``core``. Best effort: returns False on
    any failure (non-Linux, cgroup-restricted, bad core id)."""
    try:
        os.sched_setaffinity(0, {int(core)})
        return True
    except (AttributeError, OSError, ValueError):
        return False


def bind_worker(th_id: int) -> Optional[int]:
    """Bind worker ``th_id`` per the MCA binding params. Returns the core
    bound to, or None when binding is off/unavailable."""
    if not int(mca_param.get("runtime.bind_workers", 0)):
        return None
    cores = _binding_list()
    if not cores:
        return None
    core = cores[th_id % len(cores)]
    if bind_current_thread(core):
        debug_verbose(3, "binding", "worker %d bound to core %d",
                      th_id, core)
        return core
    return None


def bind_comm_thread() -> Optional[int]:
    """Bind the calling (comm) thread to ``comm.bind_core``."""
    core = int(mca_param.get("comm.bind_core", -1))
    if core < 0:
        return None
    if bind_current_thread(core):
        debug_verbose(3, "binding", "comm thread bound to core %d", core)
        return core
    return None
