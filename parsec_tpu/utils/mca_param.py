"""MCA-style runtime parameter system.

Mirrors the reference's Modular Component Architecture parameter registry
(parsec/utils/mca_param.c, ~2000 LoC): parameters are registered by
(framework, component, name), and values are resolved with priority

    explicit set()  >  environment PARSEC_MCA_<name>  >  config file  >
    registered default

Config files: ``~/.parsec/mca-params.conf`` and ``$PARSEC_MCA_PARAM_FILES``
(``key = value`` lines, ``#`` comments), matching the reference's file
search (mca_param.c file parsing).

The reference dumps all parameters on --help (parsec.c:903-918); here
:func:`dump` returns the same information programmatically.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

ENV_PREFIX = "PARSEC_MCA_"


@dataclass
class _Param:
    name: str                      # full dotted name, e.g. "sched.lfq.steal_depth"
    default: Any
    type: type
    help: str = ""
    read_only: bool = False
    # closed value set (reference: mca_base_var enum registration) —
    # resolution validates against it so a typo'd env var / set() fails
    # loudly instead of silently meaning "default"
    choices: Optional[tuple] = None
    # explicit runtime override (set()); highest priority
    override: Any = None
    has_override: bool = False

    def _validate(self, value: Any, source: str) -> Any:
        if self.choices is not None and value not in self.choices:
            raise ValueError(
                f"MCA param {self.name}: invalid value {value!r} (from "
                f"{source}); choices are {', '.join(map(str, self.choices))}")
        return value

    def resolve(self, file_values: Dict[str, str]) -> Any:
        if self.has_override:
            return self._validate(self.override, "set()")
        env_key = ENV_PREFIX + self.name.replace(".", "_")
        if env_key in os.environ:
            return self._validate(_coerce(os.environ[env_key], self.type),
                                  f"env {env_key}")
        if self.name in file_values:
            return self._validate(_coerce(file_values[self.name], self.type),
                                  "config file")
        return self.default


def _coerce(value: str, typ: type) -> Any:
    if typ is bool:
        return str(value).strip().lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(str(value).strip(), 0)
    if typ is float:
        return float(value)
    return value


class ParamRegistry:
    def __init__(self) -> None:
        self._params: Dict[str, _Param] = {}
        self._file_values: Dict[str, str] = {}
        self._files_loaded = False
        self._lock = threading.Lock()
        self._generation = 0
        self._cache: Dict[str, tuple] = {}   # name -> (generation, value)

    # -- file layer -------------------------------------------------------
    def _load_files(self) -> None:
        if self._files_loaded:
            return
        self._files_loaded = True
        paths: List[str] = []
        home = os.path.expanduser("~/.parsec/mca-params.conf")
        paths.append(home)
        extra = os.environ.get("PARSEC_MCA_PARAM_FILES", "")
        paths.extend(p for p in extra.split(os.pathsep) if p)
        for path in paths:
            try:
                with open(path) as fh:
                    for line in fh:
                        line = line.split("#", 1)[0].strip()
                        if not line or "=" not in line:
                            continue
                        key, val = line.split("=", 1)
                        self._file_values[key.strip()] = val.strip()
            except OSError:
                continue

    # -- registration / access -------------------------------------------
    def register(self, name: str, default: Any, help: str = "",
                 type: Optional[type] = None, read_only: bool = False,
                 choices: Optional[tuple] = None) -> None:
        with self._lock:
            if name in self._params:
                return
            typ = type if type is not None else (default.__class__ if default is not None else str)
            self._params[name] = _Param(name=name, default=default, type=typ,
                                        help=help, read_only=read_only,
                                        choices=tuple(choices) if choices
                                        else None)

    def get(self, name: str, default: Any = None) -> Any:
        self._load_files()
        with self._lock:
            p = self._params.get(name)
            if p is None:
                # unregistered lookups still honor env/file so components can
                # probe without registering first
                env_key = ENV_PREFIX + name.replace(".", "_")
                if env_key in os.environ:
                    raw = os.environ[env_key]
                    return _coerce(raw, default.__class__) if default is not None else raw
                if name in self._file_values:
                    raw = self._file_values[name]
                    return _coerce(raw, default.__class__) if default is not None else raw
                return default
            return p.resolve(self._file_values)

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            p = self._params.get(name)
            if p is None:
                p = _Param(name=name, default=None, type=value.__class__)
                self._params[name] = p
            if p.read_only:
                raise ValueError(f"MCA param {name} is read-only")
            p.override = value
            p.has_override = True
            self._generation += 1

    def unset(self, name: str) -> None:
        with self._lock:
            p = self._params.get(name)
            if p is not None:
                p.override, p.has_override = None, False
                self._generation += 1

    def override_of(self, name: str) -> tuple:
        """``(has_override, value)`` — the runtime-override layer only
        (env/file/default layers are process-fixed). The save half of a
        save/restore pair for harnesses that must pin knobs temporarily
        inside a LIVE process (see :meth:`restore_override`): plain
        unset() would destroy a caller's explicit pin."""
        with self._lock:
            p = self._params.get(name)
            if p is None or not p.has_override:
                return (False, None)
            return (True, p.override)

    def restore_override(self, name: str, saved: tuple) -> None:
        """Restore a knob to its :meth:`override_of` snapshot."""
        had, value = saved
        if had:
            self.set(name, value)
        else:
            self.unset(name)

    def generation(self) -> int:
        """Monotonic counter bumped by set()/unset(): hot paths cache a
        resolved value keyed by this instead of re-resolving per call
        (env/file layers are fixed after startup; runtime overrides are
        the only mid-process change channel)."""
        return self._generation

    def cached_get(self, name: str, default: Any = None) -> Any:
        """``get`` memoized by :meth:`generation` — for per-message hot
        paths (a full ``get`` resolves env vars per call, ~3 µs; this is
        a dict hit + one int compare). Unlocked by design: a racing
        ``set`` at worst causes one redundant re-resolve.

        Env-var caveat (intended): the generation counter only bumps on
        ``set()``/``unset()``, so an IN-PROCESS ``os.environ`` change
        (e.g. mutating ``PARSEC_MCA_comm_eager_limit`` after startup)
        that a plain :meth:`get` would honor is NOT seen here until the
        next ``set()``/``unset()`` of ANY param. Change parameters at
        runtime through ``set()`` — that is what the runtime and every
        test do; env vars are a process-startup channel."""
        gen = self._generation
        hit = self._cache.get(name)
        if hit is not None and hit[0] == gen:
            return hit[1]
        val = self.get(name, default)
        self._cache[name] = (gen, val)
        return val

    def dump(self) -> List[Dict[str, Any]]:
        """All registered params with current values (parsec --help analog)."""
        self._load_files()
        with self._lock:
            return [
                {"name": p.name, "value": p.resolve(self._file_values),
                 "default": p.default, "help": p.help}
                for p in sorted(self._params.values(), key=lambda p: p.name)
            ]


_registry = ParamRegistry()

register = _registry.register
get = _registry.get
set = _registry.set
unset = _registry.unset
override_of = _registry.override_of
restore_override = _registry.restore_override
dump = _registry.dump
generation = _registry.generation
cached_get = _registry.cached_get


def parse_cli(argv: List[str]) -> List[str]:
    """Consume ``--mca key value`` pairs from argv (parsec.c:411-463 analog).

    Returns argv with the consumed arguments removed.
    """
    out: List[str] = []
    i = 0
    while i < len(argv):
        if argv[i] == "--mca" and i + 2 < len(argv):
            _registry.set(argv[i + 1], argv[i + 2])
            i += 3
        else:
            out.append(argv[i])
            i += 1
    return out
