"""Zone allocator: segment bookkeeping for a device heap.

Reference: parsec/utils/zone_malloc.c — the CUDA device module carves
one big device allocation into tile-sized segments with this allocator
(unit-granular first-fit with segment merge on free). On TPU the XLA
runtime owns physical HBM, but the device layer still needs the same
*accounting* structure to decide eviction (LRU over zone segments,
device_gpu.h:115-136) and to answer "does this tile set fit" before
scheduling a task's stage-in. Offsets returned here index a logical
heap, e.g. slots of a stacked tile store."""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple


class ZoneAllocator:
    """First-fit allocator over ``capacity`` bytes with ``unit``-byte
    granularity (zone_malloc keeps unit-counted segments)."""

    def __init__(self, capacity: int, unit: int = 512):
        if capacity <= 0 or unit <= 0:
            raise ValueError("capacity and unit must be positive")
        self.unit = unit
        # round DOWN: handing out the partial trailing unit would let a
        # full-unit write overrun the real heap
        self.nb_units = capacity // unit
        if self.nb_units == 0:
            raise ValueError(f"capacity {capacity} < one unit ({unit})")
        # free segments as sorted (start_unit, n_units)
        self._free: List[Tuple[int, int]] = [(0, self.nb_units)]
        self._used: Dict[int, int] = {}        # start_unit -> n_units
        self._lock = threading.Lock()

    @property
    def capacity(self) -> int:
        return self.nb_units * self.unit

    def bytes_free(self) -> int:
        with self._lock:
            return sum(n for _, n in self._free) * self.unit

    def bytes_used(self) -> int:
        with self._lock:
            return sum(self._used.values()) * self.unit

    def malloc(self, nbytes: int) -> Optional[int]:
        """Reserve ``nbytes``; returns the byte offset or None when no
        segment fits (caller evicts and retries — the GPU module's
        reserve/evict loop, device_cuda_module.c:864)."""
        if nbytes <= 0:
            raise ValueError("malloc size must be positive")
        units = (nbytes + self.unit - 1) // self.unit
        with self._lock:
            for idx, (start, n) in enumerate(self._free):
                if n >= units:
                    if n == units:
                        self._free.pop(idx)
                    else:
                        self._free[idx] = (start + units, n - units)
                    self._used[start] = units
                    return start * self.unit
        return None

    def free(self, offset: int) -> None:
        """Release a segment and merge with free neighbors."""
        start = offset // self.unit
        with self._lock:
            units = self._used.pop(start, None)
            if units is None:
                raise ValueError(f"free of unallocated offset {offset}")
            # sorted insert, then merge with at most the two adjacent
            # neighbors — free sits on the device eviction path
            idx = bisect.bisect_left(self._free, (start, units))
            self._free.insert(idx, (start, units))
            if idx + 1 < len(self._free) and \
                    start + units == self._free[idx + 1][0]:
                nxt = self._free.pop(idx + 1)
                self._free[idx] = (start, units + nxt[1])
            if idx > 0:
                p_start, p_units = self._free[idx - 1]
                if p_start + p_units == start:
                    cur = self._free.pop(idx)
                    self._free[idx - 1] = (p_start, p_units + cur[1])

    def fragmentation(self) -> float:
        """1 − largest_free/total_free (0 = one contiguous free block)."""
        with self._lock:
            total = sum(n for _, n in self._free)
            if total == 0:
                return 0.0
            return 1.0 - max(n for _, n in self._free) / total
