"""Shared comm-knob preamble of the multi-rank wire benches.

Every socket-mesh bench rank (serving, elastic, recovery, bcast, and
the pingpong latency harness) used to copy-paste the same three
``mca_param.set`` lines; each new comm knob then needed seven edits —
and round 11 shipped with one of the seven drifted. This helper is the
ONE pin point: host-payload wire benches measure the WIRE, so every
knob that could route payloads through an accelerator is pinned off,
including the device-plane knobs added after the copy-paste spread
(``comm.device_pipeline`` / ``comm.device_direct``).

``tpu_off=False`` keeps the accelerator device module enabled (the
device-payload pingpong rows need it); ``overrides`` lets a bench turn
individual knobs back on (e.g. the device-plane A/B arms) or pin extra
ones — overrides are applied LAST, so they always win over the
defaults pinned here.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from . import mca_param


def pin_wire_bench_env(tpu_off: bool = True,
                       overrides: Optional[Dict[str, Any]] = None
                       ) -> None:
    """Pin the wire-bench comm environment in THIS process (bench rank
    processes call it right after import, before building engines)."""
    pins: Dict[str, Any] = {
        # no stage-through collection reads, no receive staging: host
        # payload rows measure the wire, not the accelerator (measured
        # 3.8 ms -> ~170 ms/hop through the axon tunnel otherwise)
        "runtime.stage_reads": "0",
        "comm.stage_recv": "0",
        # device data plane off by default for host-payload benches —
        # the knobs only act on device arrays, but pinning them keeps
        # every bench deterministic under future auto-default changes
        "comm.device_pipeline": "0",
        "comm.device_direct": "0",
    }
    if tpu_off:
        # the rank fleet must never touch (or contend for) an
        # exclusive-access chip
        pins["device.tpu.enabled"] = False
    pins.update(overrides or {})
    for key, val in pins.items():
        mca_param.set(key, val)
