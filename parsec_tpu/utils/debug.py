"""Leveled debug/output streams with a recent-log capture ring.

Mirrors the reference's debug OUTPUT facility (parsec/utils/debug.h:
39-76, utils/output.c): verbosity-leveled streams plus a fixed-size,
thread-safe ring capturing recently FORMATTED log lines for post-mortem
dumps. The structural-event history (the reference's
``parsec_debug_history`` / debug_marks.h EXE/ACTIVATE marks) is the
separate :mod:`~parsec_tpu.utils.debug_history` module — this ring
records what was logged, that one records what the runtime did.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Deque, Tuple

_verbosity = int(os.environ.get("PARSEC_MCA_debug_verbose", "1"))
_history_size = 512
_history: Deque[Tuple[float, int, str]] = deque(maxlen=_history_size)
_lock = threading.Lock()


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = level


def get_verbosity() -> int:
    return _verbosity


def debug_verbose(level: int, stream: str, msg: str, *args) -> None:
    """parsec_debug_verbose analog: print iff level <= current verbosity,
    and always record into the history ring."""
    if args:
        msg = msg % args
    with _lock:
        _history.append((time.time(), level, f"[{stream}] {msg}"))
    if level <= _verbosity:
        print(f"parsec_tpu:{stream}: {msg}", file=sys.stderr)


def warning(stream: str, msg: str, *args) -> None:
    debug_verbose(1, stream, "WARNING: " + msg, *args)


def fatal(stream: str, msg: str, *args) -> None:
    debug_verbose(0, stream, "FATAL: " + msg, *args)
    raise RuntimeError(f"[{stream}] {msg % args if args else msg}")


def history_dump() -> str:
    """Dump the recent-LOG capture ring (formatted lines). For the
    structural EXE/ACTIVATE mark history use
    ``parsec_tpu.utils.debug_history.dump``."""
    with _lock:
        lines = [f"{t:.6f} [{lvl}] {m}" for (t, lvl, m) in _history]
    return "\n".join(lines)


def history_clear() -> None:
    with _lock:
        _history.clear()
