"""Debug-history ring: always-cheap in-memory marks, dumped on demand.

Reference: the PARSEC_DEBUG_HISTORY build (parsec/utils/debug.h:41-63
``parsec_debug_history_add/dump/purge``, parsec/debug_marks.h
``DEBUG_MARK_EXE`` / ``DEBUG_MARK_CTL_MSG_ACTIVATE_SENT`` / ...):
per-thread ring buffers record scheduling and wire events with
negligible overhead, and the whole interleaved history is dumped when a
race or hang is being chased — the "what was every thread doing right
before it went wrong" tool that asserts alone can't provide.

TPU build analog: per-thread rings of ``(t, ring-id, fmt, args)``
tuples — the hot path is one cached-size check plus a lock-free deque
append (formatting deferred to dump time; the enabled-size is cached
against the MCA registry generation, so the disabled path is a dict
miss-free comparison). Rings are identified by a monotonic id, never by
``threading.get_ident()`` — ident reuse after a thread exits must not
overwrite a dead thread's marks (often exactly the post-mortem
evidence); dead rings are retained up to ``_MAX_RINGS`` then dropped
oldest-first. Enabled with ``debug.history_size > 0``; fatal paths
(task-body errors, comm AM-handler crashes) dump automatically,
matching ``parsec_debug_history_on_fatal``.

(`utils.debug.history_dump` is a different facility — a capture of
recent formatted LOG lines; this module records structural marks.)
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Tuple

from . import mca_param

mca_param.register("debug.history_size", 0,
                   help="per-thread debug-history ring length "
                        "(0 = disabled; the reference's "
                        "PARSEC_DEBUG_HISTORY build knob)")

_MAX_RINGS = 256          # dead-thread rings retained before eviction

_rings: Dict[int, Deque[Tuple[float, str, tuple]]] = {}
_rings_lock = threading.Lock()          # protects the dict, not the rings
_ring_seq = [0]
_local = threading.local()
# (registry generation, resolved size): one int compare per mark()
_size_cache: Tuple[int, int] = (-1, 0)


def _size() -> int:
    global _size_cache
    gen = mca_param.generation()
    cached_gen, cached = _size_cache
    if cached_gen != gen:
        cached = int(mca_param.get("debug.history_size", 0))
        _size_cache = (gen, cached)
    return cached


def enabled() -> bool:
    return _size() > 0


def mark(fmt: str, *args: Any) -> None:
    """Record one event in this thread's ring (no-op when disabled).
    ``fmt % args`` is deferred to dump time — the hot path stores
    references only (debug_history_add analog)."""
    size = _size()
    if size <= 0:
        return
    ring = getattr(_local, "ring", None)
    if ring is None or ring.maxlen != size:
        ring = deque(maxlen=size)
        _local.ring = ring
        with _rings_lock:
            _ring_seq[0] += 1
            _rings[_ring_seq[0]] = ring
            while len(_rings) > _MAX_RINGS:       # oldest-first eviction
                _rings.pop(next(iter(_rings)))
    ring.append((time.perf_counter(), fmt, args))


def dump(purge: bool = False) -> List[str]:
    """Interleave every ring (live and dead-thread) by timestamp and
    render it (parsec_debug_history_dump). ``purge=True`` clears
    afterwards."""
    with _rings_lock:
        items = [(t, rid, fmt, args)
                 for rid, ring in _rings.items()
                 for (t, fmt, args) in list(ring)]
        if purge:
            for ring in _rings.values():
                ring.clear()
    items.sort(key=lambda it: it[0])
    out = []
    for (t, rid, fmt, args) in items:
        try:
            msg = fmt % args if args else fmt
        except Exception:  # noqa: BLE001 — a bad mark must not mask the dump
            msg = f"{fmt!r} % {args!r}"
        out.append(f"[{t:.6f}] ring-{rid}: {msg}")
    return out


def purge() -> None:
    """Drop all recorded history (parsec_debug_history_purge)."""
    with _rings_lock:
        for ring in _rings.values():
            ring.clear()


def dump_on_fatal(reason: str, tail: int = 200) -> None:
    """Emit the history through the warning logger when a fatal error
    path fires (parsec_debug_history_on_fatal analog)."""
    if not enabled():
        return
    from .debug import warning
    lines = dump()
    shown = lines[-tail:]
    warning("debug_history", "fatal (%s): showing last %d of %d "
            "history marks", reason, len(shown), len(lines))
    for line in shown:
        warning("debug_history", "%s", line)
