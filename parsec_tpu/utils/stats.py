"""Tiny shared statistics helpers for bench/serving reporting."""

from __future__ import annotations

from typing import List, Optional


def pctl(xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 1]) of ``xs``; None when
    empty. ONE definition — the serving, elastic, and autoscaler p99
    figures must never diverge on the index formula."""
    if not xs:
        return None
    s = sorted(xs)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]
