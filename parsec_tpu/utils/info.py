"""Extensible info registry (parsec_info_t analog).

Reference: ``parsec/class/info.c/h`` (559 LoC) + the per-object info
arrays wired into taskpools, devices and streams
(``parsec_internal.h:688-702``). The reference registers named info
slots once (getting back an index), then every carrier object lazily
materializes per-slot objects via a constructor, so MCA modules can hang
arbitrary state off runtime objects without touching their structs.

Same contract here: :class:`InfoRegistry` maps names → slot ids;
:class:`InfoArray` is the per-carrier store with lazy per-slot
construction. Used for per-device / per-stream extension data (PINS
modules, device statistics extensions) without subclassing.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional


class InfoRegistry:
    """Process-wide named info slots (parsec_info_register analog)."""

    def __init__(self) -> None:
        self._slots: Dict[str, int] = {}
        self._ctors: List[Optional[Callable[[Any], Any]]] = []
        self._lock = threading.Lock()

    def register(self, name: str,
                 constructor: Optional[Callable[[Any], Any]] = None) -> int:
        """Register (or look up) slot ``name``; returns its id. The
        constructor builds the initial per-carrier value lazily, taking
        the carrier object."""
        with self._lock:
            sid = self._slots.get(name)
            if sid is not None:
                if constructor is not None:
                    self._ctors[sid] = constructor
                return sid
            sid = len(self._ctors)
            self._slots[name] = sid
            self._ctors.append(constructor)
            return sid

    def lookup(self, name: str) -> Optional[int]:
        with self._lock:
            return self._slots.get(name)

    def unregister(self, name: str) -> None:
        """Drop the name→slot binding (slot ids are never reused —
        carriers may still hold values; reference semantics)."""
        with self._lock:
            self._slots.pop(name, None)

    def constructor(self, sid: int) -> Optional[Callable]:
        with self._lock:
            return self._ctors[sid] if 0 <= sid < len(self._ctors) \
                else None

    def names(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._slots)


class InfoArray:
    """Per-carrier slot values with lazy construction
    (parsec_info_object_array analog)."""

    def __init__(self, registry: InfoRegistry, carrier: Any = None):
        self.registry = registry
        self.carrier = carrier
        self._values: Dict[int, Any] = {}
        self._lock = threading.Lock()

    def get(self, slot, default: Any = None) -> Any:
        sid = self.registry.lookup(slot) if isinstance(slot, str) else slot
        if sid is None:
            return default
        with self._lock:
            if sid in self._values:
                return self._values[sid]
            ctor = self.registry.constructor(sid)
            if ctor is None:
                return default
            val = ctor(self.carrier)
            self._values[sid] = val
            return val

    def set(self, slot, value: Any) -> None:
        sid = self.registry.lookup(slot) if isinstance(slot, str) else slot
        if sid is None:
            raise KeyError(f"unknown info slot {slot!r}")
        with self._lock:
            self._values[sid] = value

    def clear(self, slot) -> None:
        sid = self.registry.lookup(slot) if isinstance(slot, str) else slot
        if sid is not None:
            with self._lock:
                self._values.pop(sid, None)


# the process-wide registries the reference exposes as globals
# (parsec_per_device_infos, parsec_per_stream_infos)
per_device_infos = InfoRegistry()
per_stream_infos = InfoRegistry()
