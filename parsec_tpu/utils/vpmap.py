"""Virtual-process map (reference parsec/vpmap.c, 663 LoC).

A vpmap partitions a context's execution streams into *virtual
processes*; work stealing never crosses a VP boundary (parsec.c:336-382).
The reference initializes the map from one of: flat (all streams in one
VP), fixed-size groups, a description file, or hwloc topology
(vpmap_init_from_{flat,parameters,file,hardware_affinity}).

Spec grammar for the ``vpmap`` MCA param:

- ``flat``              — one VP spanning every stream (default)
- ``nb:SIZE``           — VPs of SIZE consecutive streams
- ``list:0,0,1,1,...``  — explicit per-stream VP ids
- ``file:PATH``         — one line per VP: the number of streams in it
"""

from __future__ import annotations

from typing import List


def parse(spec: str, nb_cores: int) -> List[int]:
    """Return the vp id of each of ``nb_cores`` streams."""
    spec = (spec or "flat").strip()
    if spec == "flat":
        return [0] * nb_cores
    if spec.startswith("nb:"):
        size = max(1, int(spec[3:]))
        return [i // size for i in range(nb_cores)]
    if spec.startswith("list:"):
        ids = [int(x) for x in spec[5:].split(",") if x.strip() != ""]
        if len(ids) != nb_cores:
            # truncating a longer map could silently drop whole VPs (or
            # leave non-dense ids) — require an exact match
            raise ValueError(
                f"vpmap list names {len(ids)} streams, context has "
                f"{nb_cores}")
        _check_dense(ids)
        return ids
    if spec.startswith("file:"):
        sizes: List[int] = []
        with open(spec[5:]) as fh:
            for line in fh:
                line = line.split("#", 1)[0].strip()
                if line:
                    size = int(line)
                    if size <= 0:
                        raise ValueError(
                            f"vpmap file: VP size must be positive, "
                            f"got {size}")
                    sizes.append(size)
        ids = [vp for vp, size in enumerate(sizes) for _ in range(size)]
        if len(ids) > nb_cores:
            # truncation would silently drop whole VPs (same rule as
            # list: specs)
            raise ValueError(
                f"vpmap file names {len(ids)} streams, context has "
                f"{nb_cores}")
        if len(ids) < nb_cores:
            # remaining streams join a final VP (reference pads likewise)
            ids.extend([len(sizes)] * (nb_cores - len(ids)))
        _check_dense(ids)
        return ids
    raise ValueError(f"unknown vpmap spec {spec!r} "
                     "(flat | nb:SIZE | list:... | file:PATH)")


def _check_dense(ids: List[int]) -> None:
    """VP ids must be 0..max contiguous (the reference indexes
    context->virtual_processes by vp id)."""
    seen = sorted(set(ids))
    if seen != list(range(len(seen))):
        raise ValueError(f"vpmap ids must be dense 0..N-1, got {seen}")


def nb_vps(ids: List[int]) -> int:
    return max(ids) + 1 if ids else 0
