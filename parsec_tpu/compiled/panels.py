"""Panel-fused executor: wavefront plans over a dense transposed array.

The tile-dict/stacked-store executors (wavefront.py) run each wave-group
as a gather → batched body → scatter. That is the right general shape,
but for dense one-matrix DAGs (POTRF/GEQRF-like) the data movement
dominates on TPU: every task's tiles are stacked (copied) before compute
and re-sliced after — measured ~3x the compute floor for tiled POTRF at
NT=8..16 — and batched (vmapped) matmuls themselves reach only ~92 TF/s
on a v5e chip where plain 2D matmuls of any aspect ratio reach ~166-177.

This executor is the next fusion level, the wave-granular analog of the
chore ``batch_hook`` (core.task.Chore): the *taskpool* registers a
``wave_fuser`` that lowers an ENTIRE wave's groups to a few dense-slice
operations against the matrix stored as ONE ``(N, M)`` HBM array holding
**Aᵀ** (row panel j of the store = block-column j of A). The transposed
layout makes every panel write a leading-dimension contiguous
dynamic-update-slice (in-place under jit), and panel reads are strided
slices XLA fuses into the matmuls. Measured effect for tiled POTRF on a
v5e chip: the left-looking fused form reaches ~98-110 TF/s where the
per-tile executors topped out at ~45.

Slot bookkeeping comes from the SAME :class:`~.wavefront.WavefrontPlan` —
planning, leveling, and hazard verification are unchanged; only the data
substrate changes. ``write_back`` honors the DAG's write-set: tiles no
task writes are never copied back, so collection-level semantics match
the tiled executors even if the substrate scribbles on cells the DAG
never reads.

Reference analog: the reference reaches peak by handing whole-tile
operations to vendor BLAS inside .jdf bodies and letting lookahead keep
the GPU busy (dplasma dpotrf + device_cuda_module.c pipeline). Here the
fusion brings whole *panels* to the MXU — the TPU-idiomatic equivalent —
while the PTG DAG still defines and validates the schedule.

A wave_fuser has signature::

    fuser(wave: List[WaveGroup], geom: PanelGeometry)
        -> Callable[[dict], dict] | None

taking/returning the executor state — a dict with one transposed dense
array per collection, keyed by collection name (``geom.name``); fusers
may stash extra carry entries (underscore-prefixed by convention, e.g. a
factored diagonal inverse consumed by the next wave). ``geom`` is always
the ``{name: PanelGeometry}`` dict; single-collection fusers unpack
their one entry. Return None to
reject a wave (the executor then refuses, naming it — no silent
fallback; a hybrid would reintroduce the copies this path avoids).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Set, Tuple

import numpy as np

from .wavefront import WavefrontPlan
from ..utils.debug import debug_verbose


@dataclass(frozen=True)
class PanelGeometry:
    """Transposed-dense layout geometry handed to wave fusers: the state
    array ``state[name]`` is ``(nb*nt, mb*mt)`` holding the collection
    transposed — tile (i, j) lives at ``D[cols(j), rows(i)]``
    transposed."""
    name: str
    mb: int
    nb: int
    mt: int
    nt: int

    def rows(self, i: int) -> slice:
        """Column range of D covering block-row i of A."""
        return slice(i * self.mb, (i + 1) * self.mb)

    def cols(self, j: int) -> slice:
        """Row range of D covering block-column j of A."""
        return slice(j * self.nb, (j + 1) * self.nb)


class PanelExecutor:
    """Execute a :class:`WavefrontPlan` over transposed dense storage.

    Requirements (checked): the taskpool registered ``wave_fuser`` and
    every collection is a tiled matrix. :meth:`run_state` is a pure
    jittable function ``state -> state``
    (state = ``{collection name: transposed dense array, ...carries}``).
    """

    def __init__(self, plan: WavefrontPlan):
        import jax
        self.jax = jax
        self.plan = plan
        fuser = getattr(plan.taskpool, "wave_fuser", None)
        if fuser is None:
            raise ValueError(
                f"taskpool {plan.taskpool.name!r} registers no wave_fuser; "
                "use the tile-dict/stacked executors instead")
        if getattr(plan, "has_reshapes", False):
            raise ValueError(
                f"taskpool {plan.taskpool.name!r} declares dep "
                "[type=...] reshape specs; wave fusers lower raw panel "
                "slices — use the tile-dict executors (which apply "
                "specs at gather) or the host runtime")
        self.geoms = {
            name: PanelGeometry(name=name, mb=dc.mb, nb=dc.nb,
                                mt=dc.mt, nt=dc.nt)
            for name, dc in plan.collections.items()}
        # fusers always receive the {name: PanelGeometry} dict —
        # uniform, no type sniffing (single-collection fusers unpack
        # their one entry)
        geom_arg = self.geoms
        self.geom = geom_arg
        # lower every wave up front — planning errors surface at build
        # time, not mid-trace
        self._wave_fns: List[Callable] = []
        for w, wave in enumerate(plan.waves):
            fn = fuser(wave, geom_arg)
            if fn is None:
                names = [(g.tc.name, len(g.tasks)) for g in wave]
                raise ValueError(
                    f"wave {w} not fusable by {plan.taskpool.name!r}: "
                    f"{names}")
            self._wave_fns.append(fn)
        # DAG write-set per collection: (i, j) block coords any task writes
        self._written: Dict[str, Set[Tuple[int, int]]] = {
            name: set() for name in self.geoms}
        invmaps = {name: {s: k for k, s in plan.slot_maps[name].items()}
                   for name in self.geoms}
        for wave in plan.waves:
            for grp in wave:
                for (name, slots) in grp.out_slots:
                    for s in slots:
                        self._written[name].add(
                            tuple(invmaps[name][int(s)]))
        debug_verbose(3, "panels", "lowered %s: %d waves onto %d "
                      "transposed dense arrays", plan.taskpool.name,
                      len(self._wave_fns), len(self.geoms))
        self.jitted = self.jax.jit(self.run_state, donate_argnums=0)

    # -- pure dense execution --------------------------------------------
    def run_state(self, state: Dict[str, Any]) -> Dict[str, Any]:
        state = dict(state)
        for fn in self._wave_fns:
            state = fn(state)
        # fuser carries (factored inverses etc.) are wave-transient —
        # only the collection arrays survive
        return {name: state[name] for name in self.geoms}

    # -- host-driven convenience -----------------------------------------
    def make_state(self) -> Dict[str, Any]:
        """Collection tiles → transposed dense state, one array per
        collection."""
        import jax.numpy as jnp
        state = {}
        for name, g in self.geoms.items():
            dc = self.plan.collections[name]
            rows = []
            for j in range(g.nt):
                rows.append(jnp.concatenate(
                    [jnp.asarray(dc.data_of((i, j))).T
                     for i in range(g.mt)], axis=1))
            state[name] = jnp.concatenate(rows, axis=0)
        return state

    def write_back(self, state: Dict[str, Any]) -> None:
        """Write ONLY the DAG's write-set back to the collections —
        substrate scribbles outside it stay invisible at the collection
        level."""
        for name, g in self.geoms.items():
            if not self._written[name]:
                continue
            dc = self.plan.collections[name]
            host = np.asarray(state[name])
            for (i, j) in sorted(self._written[name]):
                dc.write_tile((i, j), host[g.cols(j), g.rows(i)].T)

    def run(self, jit: bool = True) -> float:
        t0 = time.perf_counter()
        state = self.make_state()
        fn = self.jitted if jit else self.run_state
        out = fn(state)
        for v in out.values():
            v.block_until_ready()
        dt = time.perf_counter() - t0
        self.write_back(out)
        return dt
