"""Panel-fused executor: wavefront plans over a dense transposed array.

The tile-dict/stacked-store executors (wavefront.py) run each wave-group
as a gather → batched body → scatter. That is the right general shape,
but for dense one-matrix DAGs (POTRF/GEQRF-like) the data movement
dominates on TPU: every task's tiles are stacked (copied) before compute
and re-sliced after — measured ~3x the compute floor for tiled POTRF at
NT=8..16 — and batched (vmapped) matmuls themselves reach only ~92 TF/s
on a v5e chip where plain 2D matmuls of any aspect ratio reach ~166-177.

This executor is the next fusion level, the wave-granular analog of the
chore ``batch_hook`` (core.task.Chore): the *taskpool* registers a
``wave_fuser`` that lowers an ENTIRE wave's groups to a few dense-slice
operations against the matrix stored as ONE ``(N, M)`` HBM array holding
**Aᵀ** (row panel j of the store = block-column j of A). The transposed
layout makes every panel write a leading-dimension contiguous
dynamic-update-slice (in-place under jit), and panel reads are strided
slices XLA fuses into the matmuls. Measured effect for tiled POTRF on a
v5e chip: the left-looking fused form reaches ~98-110 TF/s where the
per-tile executors topped out at ~45.

Slot bookkeeping comes from the SAME :class:`~.wavefront.WavefrontPlan` —
planning, leveling, and hazard verification are unchanged; only the data
substrate changes. ``write_back`` honors the DAG's write-set: tiles no
task writes are never copied back, so collection-level semantics match
the tiled executors even if the substrate scribbles on cells the DAG
never reads.

Reference analog: the reference reaches peak by handing whole-tile
operations to vendor BLAS inside .jdf bodies and letting lookahead keep
the GPU busy (dplasma dpotrf + device_cuda_module.c pipeline). Here the
fusion brings whole *panels* to the MXU — the TPU-idiomatic equivalent —
while the PTG DAG still defines and validates the schedule.

A wave_fuser has signature::

    fuser(wave: List[WaveGroup], geom: PanelGeometry)
        -> Callable[[dict], dict] | None

taking/returning the executor state — a dict with one transposed dense
array per collection, keyed by collection name (``geom.name``); fusers
may stash extra carry entries (underscore-prefixed by convention, e.g. a
factored diagonal inverse consumed by the next wave). ``geom`` is always
the ``{name: PanelGeometry}`` dict; single-collection fusers unpack
their one entry. Return None to
reject a wave (the executor then refuses, naming it — no silent
fallback; a hybrid would reintroduce the copies this path avoids).

Compile-once serving (the segmented panel path)
-----------------------------------------------

Whole-DAG jit of the fused program is the fastest *runtime* form but
its compile time is linear in waves and specific to N — every new
problem size is a fresh multi-second lowering (PARITY compile-time
table). The **segmented** path restores PaRSEC's compile-per-task-class
economy: a taskpool may additionally register a ``panel_segment_fuser``
that lowers each wave to :class:`SegStep` descriptors — named *panel
kernels* over extracted panels whose shapes are rounded up to a small
**bucket lattice** (:func:`bucket_tiles`: exact up to 16 tiles, then
multiples of 2^(⌊log₂t⌋−3) → ≤12.5% padding per dim, O(16·log NT)
buckets; grids of ≤16 tiles never pad at all).
Padding is exact-by-construction: extraction zero-masks beyond the true
extent, write-back masks to the true extent (and shifts windows clamped
at the array edge), so padded lanes carry zeros through the math.

The heavy kernels are keyed by (kernel, NB, bucket shape, dtype, body
hooks/trace knobs) — **independent of N** — and enter the shared
in-process jit store and the persistent executor store
(``utils/compile_cache.py``): a new N at an already-served (NB, dtype)
re-uses every already-compiled bucket, and a second run (or second
process) pays zero XLA compiles. Only the thin extract/write programs
are keyed per state shape (they are slice+mask copies, cheap to
compile, and they persist too).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from .wavefront import WavefrontPlan, plan_structure_fingerprint
from ..utils import compile_cache
from ..utils.debug import debug_verbose


@dataclass(frozen=True)
class PanelGeometry:
    """Transposed-dense layout geometry handed to wave fusers: the state
    array ``state[name]`` is ``(nb*nt, mb*mt)`` holding the collection
    transposed — tile (i, j) lives at ``D[cols(j), rows(i)]``
    transposed."""
    name: str
    mb: int
    nb: int
    mt: int
    nt: int

    def rows(self, i: int) -> slice:
        """Column range of D covering block-row i of A."""
        return slice(i * self.mb, (i + 1) * self.mb)

    def cols(self, j: int) -> slice:
        """Row range of D covering block-column j of A."""
        return slice(j * self.nb, (j + 1) * self.nb)


# ---------------------------------------------------------------------------
# bucket lattice (segmented panel path)
# ---------------------------------------------------------------------------

def bucket_tiles(t: int, cap: int) -> int:
    """Round a tile count up to the bucket lattice, capped at ``cap``
    (the dimension's grid extent — buckets never exceed the store).

    Lattice: exact for t ≤ 16, then multiples of 2^(⌊log₂t⌋−3)
    ({18,20,...,32, 36,40,...,64, 72,...} — ≤16 points per octave) —
    padding overhead ≤ 12.5% per dimension, O(16·log NT) distinct
    buckets, and the lattice points are absolute (N-independent) so a
    smaller problem at the same NB lands entirely on already-compiled
    buckets (modulo its own cap point)."""
    if t >= cap:
        return cap
    q = 1 << max(0, t.bit_length() - 1 - 3)
    return min(((t + q - 1) // q) * q, cap)


@dataclass(frozen=True, eq=False)
class SegRead:
    """One kernel input: a masked bucketed window of a state array
    (``src="state"``), a carry produced by an earlier step
    (``src="carry"``), or a lowering-time constant (``src="const"``).
    Offsets/extents are element units; ``rows_b/cols_b`` are the
    bucketed extents actually extracted (≥ true, zero-masked)."""
    src: str
    name: str
    r0: int = 0
    c0: int = 0
    rows: int = 0
    cols: int = 0
    rows_b: int = 0
    cols_b: int = 0
    value: Any = None          # src="const" payload (host array/scalar)


@dataclass(frozen=True, eq=False)
class SegWrite:
    """One kernel output destination: a masked window of a state array
    (only ``[r0:r0+rows, c0:c0+cols]`` is written, whatever the padded
    value shape) or a named carry."""
    dst: str
    name: str
    r0: int = 0
    c0: int = 0
    rows: int = 0
    cols: int = 0


@dataclass(frozen=True, eq=False)
class SegStep:
    """One dispatch of a registered panel kernel: gather ``reads``,
    call the kernel, route outputs to ``writes`` (position-matched).
    ``static`` is extra kernel-builder config baked into the cache
    key (must be canonical primitives)."""
    kernel: str
    reads: Tuple[SegRead, ...]
    writes: Tuple[SegWrite, ...]
    static: Tuple = field(default=())


_PANEL_KERNELS: Dict[str, Callable] = {}


def register_panel_kernel(name: str):
    """Register a panel-kernel builder: ``builder(in_sds, static) ->
    pure fn(*arrays) -> array | tuple``. ``in_sds`` are the (bucketed)
    input ShapeDtypeStructs. Builders may read trace-affecting MCA
    knobs at build time — register those via
    :func:`~..utils.compile_cache.register_trace_knob` so the cache key
    covers them."""
    def deco(builder):
        _PANEL_KERNELS[name] = builder
        return builder
    return deco


def _build_extract(rows_b: int, cols_b: int, clamp_r: bool,
                   clamp_c: bool):
    """Masked bucketed window read: ``(D, r0, c0, rows, cols) ->
    (rows_b, cols_b)`` with zeros beyond the true extent. When the
    window can run off the array edge (static ``clamp_*`` decided at
    lowering from the descriptor), the slice start is clamped and the
    payload rolled back into place — dynamic_slice would otherwise
    silently shift the window."""
    def ext(D, r0, c0, rows, cols):
        import jax.numpy as jnp
        from jax import lax
        ra, ca = r0, c0
        if clamp_r:
            ra = jnp.minimum(r0, D.shape[0] - rows_b)
        if clamp_c:
            ca = jnp.minimum(c0, D.shape[1] - cols_b)
        raw = lax.dynamic_slice(D, (ra, ca), (rows_b, cols_b))
        if clamp_r:
            raw = jnp.roll(raw, -(r0 - ra), axis=0)
        if clamp_c:
            raw = jnp.roll(raw, -(c0 - ca), axis=1)
        rmask = jnp.arange(rows_b) < rows
        cmask = jnp.arange(cols_b) < cols
        return jnp.where(rmask[:, None] & cmask[None, :], raw,
                         jnp.zeros((), D.dtype))
    return ext


def _build_write(rows_b: int, cols_b: int, clamp_r: bool, clamp_c: bool):
    """Masked bucketed window write: only ``[r0:r0+rows, c0:c0+cols]``
    of D changes; padded lanes of V are discarded. D is donated — the
    update is in-place under XLA aliasing."""
    def wr(D, V, r0, c0, rows, cols):
        import jax.numpy as jnp
        from jax import lax
        ra, ca = r0, c0
        if clamp_r:
            ra = jnp.minimum(r0, D.shape[0] - rows_b)
        if clamp_c:
            ca = jnp.minimum(c0, D.shape[1] - cols_b)
        ro, co = r0 - ra, c0 - ca
        cur = lax.dynamic_slice(D, (ra, ca), (rows_b, cols_b))
        Vr = V.astype(D.dtype)
        if clamp_r:
            Vr = jnp.roll(Vr, ro, axis=0)
        if clamp_c:
            Vr = jnp.roll(Vr, co, axis=1)
        ri = jnp.arange(rows_b)
        ci = jnp.arange(cols_b)
        rmask = (ri >= ro) & (ri < ro + rows)
        cmask = (ci >= co) & (ci < co + cols)
        blended = jnp.where(rmask[:, None] & cmask[None, :], Vr, cur)
        return lax.dynamic_update_slice(D, blended, (ra, ca))
    return wr


class PanelExecutor:
    """Execute a :class:`WavefrontPlan` over transposed dense storage.

    Requirements (checked): the taskpool registered ``wave_fuser`` and
    every collection is a tiled matrix. :meth:`run_state` is a pure
    jittable function ``state -> state``
    (state = ``{collection name: transposed dense array, ...carries}``).
    """

    def __init__(self, plan: WavefrontPlan):
        import jax
        self.jax = jax
        self.plan = plan
        fuser = getattr(plan.taskpool, "wave_fuser", None)
        if fuser is None:
            raise ValueError(
                f"taskpool {plan.taskpool.name!r} registers no wave_fuser; "
                "use the tile-dict/stacked executors instead")
        if getattr(plan, "has_reshapes", False):
            raise ValueError(
                f"taskpool {plan.taskpool.name!r} declares dep "
                "[type=...] reshape specs; wave fusers lower raw panel "
                "slices — use the tile-dict executors (which apply "
                "specs at gather) or the host runtime")
        self.geoms = {
            name: PanelGeometry(name=name, mb=dc.mb, nb=dc.nb,
                                mt=dc.mt, nt=dc.nt)
            for name, dc in plan.collections.items()}
        # fusers always receive the {name: PanelGeometry} dict —
        # uniform, no type sniffing (single-collection fusers unpack
        # their one entry)
        geom_arg = self.geoms
        self.geom = geom_arg
        # lower every wave up front — planning errors surface at build
        # time, not mid-trace
        self._wave_fns: List[Callable] = []
        for w, wave in enumerate(plan.waves):
            fn = fuser(wave, geom_arg)
            if fn is None:
                names = [(g.tc.name, len(g.tasks)) for g in wave]
                raise ValueError(
                    f"wave {w} not fusable by {plan.taskpool.name!r}: "
                    f"{names}")
            self._wave_fns.append(fn)
        # DAG write-set per collection: (i, j) block coords any task writes
        self._written: Dict[str, Set[Tuple[int, int]]] = {
            name: set() for name in self.geoms}
        invmaps = {name: {s: k for k, s in plan.slot_maps[name].items()}
                   for name in self.geoms}
        for wave in plan.waves:
            for grp in wave:
                for (name, slots) in grp.out_slots:
                    for s in slots:
                        self._written[name].add(
                            tuple(invmaps[name][int(s)]))
        debug_verbose(3, "panels", "lowered %s: %d waves onto %d "
                      "transposed dense arrays", plan.taskpool.name,
                      len(self._wave_fns), len(self.geoms))
        # segmented (compile-once) path, lowered lazily on first use
        self._segment_fuser = getattr(plan.taskpool,
                                      "panel_segment_fuser", None)
        self._seg_steps: Optional[List[SegStep]] = None
        self._jitted = None

    @property
    def supports_segments(self) -> bool:
        return self._segment_fuser is not None

    # -- whole-DAG jit (shared + persistent) ------------------------------
    # jit caches by FUNCTION OBJECT: a fresh jax.jit(self.run_state) per
    # executor used to re-trace (and re-lower, and re-XLA) the whole
    # program for every rebuild of an identical plan. The monolith now
    # routes through the shared keyed store: equal (plan structure,
    # fuser code, shapes, trace knobs) → one trace per process and a
    # serialized executable across processes.
    @property
    def jitted(self) -> Callable:
        if self._jitted is None:
            key = self.monolith_cache_key()
            if key is None:      # unstable fingerprint: per-instance jit
                self._jitted = self.jax.jit(self.run_state,
                                            donate_argnums=0)
            else:
                self._jitted = compile_cache.cached_jit(
                    self.run_state, key=key,
                    example_args=(self.state_shapes(),),
                    donate_argnums=0)
        return self._jitted

    def state_shapes(self) -> Dict[str, Any]:
        """Abstract (ShapeDtypeStruct) state as :meth:`make_state`
        builds it — the AOT lowering input."""
        import jax
        return {name: jax.ShapeDtypeStruct(
            (g.nb * g.nt, g.mb * g.mt),
            np.dtype(self.plan.collections[name].dtype))
            for name, g in self.geoms.items()}

    def monolith_cache_key(self) -> Optional[Tuple]:
        """Semantic cache key of the whole-DAG fused program, or None
        when some ingredient has no stable fingerprint."""
        fuser = getattr(self.plan.taskpool, "wave_fuser", None)
        f_ok, f_fp = compile_cache.function_fingerprint(fuser)
        p_ok, p_fp = plan_structure_fingerprint(self.plan)
        if not (f_ok and p_ok):
            return None
        shapes = tuple(sorted(
            (name, tuple(s.shape), str(s.dtype))
            for name, s in self.state_shapes().items()))
        return ("panel_monolith", f_fp, p_fp, shapes)

    # -- pure dense execution --------------------------------------------
    def run_state(self, state: Dict[str, Any]) -> Dict[str, Any]:
        state = dict(state)
        for fn in self._wave_fns:
            state = fn(state)
        # fuser carries (factored inverses etc.) are wave-transient —
        # only the collection arrays survive
        return {name: state[name] for name in self.geoms}

    # -- host-driven convenience -----------------------------------------
    def make_state(self) -> Dict[str, Any]:
        """Collection tiles → transposed dense state, one array per
        collection."""
        import jax.numpy as jnp
        state = {}
        for name, g in self.geoms.items():
            dc = self.plan.collections[name]
            rows = []
            for j in range(g.nt):
                rows.append(jnp.concatenate(
                    [jnp.asarray(dc.data_of((i, j))).T
                     for i in range(g.mt)], axis=1))
            state[name] = jnp.concatenate(rows, axis=0)
        return state

    def write_back(self, state: Dict[str, Any]) -> None:
        """Write ONLY the DAG's write-set back to the collections —
        substrate scribbles outside it stay invisible at the collection
        level."""
        for name, g in self.geoms.items():
            if not self._written[name]:
                continue
            dc = self.plan.collections[name]
            host = np.asarray(state[name])
            for (i, j) in sorted(self._written[name]):
                dc.write_tile((i, j), host[g.cols(j), g.rows(i)].T)

    # -- segmented execution (compile-once serving) -----------------------

    def segments(self) -> List[SegStep]:
        """Lower every wave through the taskpool's
        ``panel_segment_fuser`` (lazily, cached). Raises when the
        taskpool registers none or a wave is rejected — no silent
        fallback to the linear-in-waves monolith."""
        if self._seg_steps is not None:
            return self._seg_steps
        if self._segment_fuser is None:
            raise ValueError(
                f"taskpool {self.plan.taskpool.name!r} registers no "
                "panel_segment_fuser; use the whole-DAG fused form "
                "(run/jitted) or the tile-dict segmented executor")
        steps: List[SegStep] = []
        for w, wave in enumerate(self.plan.waves):
            lowered = self._segment_fuser(wave, self.geoms)
            if lowered is None:
                names = [(g.tc.name, len(g.tasks)) for g in wave]
                raise ValueError(
                    f"wave {w} not segment-fusable by "
                    f"{self.plan.taskpool.name!r}: {names}")
            steps.extend(lowered)
        self._seg_steps = steps
        debug_verbose(3, "panels", "segment-lowered %s: %d waves -> %d "
                      "steps", self.plan.taskpool.name,
                      len(self.plan.waves), len(steps))
        return steps

    @staticmethod
    def _window_fn(D_sds, val_sds, rd_or_wr, tag):
        """Shared-cache entry for one extract/write program. Keyed by
        (state shape, bucket shape, clamp flags) — these are the only
        per-N programs of the segmented path (thin slice+mask copies);
        the heavy kernels are N-independent."""
        import jax
        clamp_r = rd_or_wr.r0 + val_sds.shape[0] > D_sds.shape[0]
        clamp_c = rd_or_wr.c0 + val_sds.shape[1] > D_sds.shape[1]
        i32 = jax.ShapeDtypeStruct((), np.int32)
        key = (tag, tuple(D_sds.shape), str(D_sds.dtype),
               tuple(val_sds.shape), clamp_r, clamp_c)
        if tag == "panel_write":
            fn = _build_write(*val_sds.shape, clamp_r, clamp_c)
            ex = (D_sds, val_sds, i32, i32, i32, i32)
            return compile_cache.cached_jit(fn, key=key, example_args=ex,
                                            donate_argnums=0)
        fn = _build_extract(*val_sds.shape, clamp_r, clamp_c)
        ex = (D_sds, i32, i32, i32, i32)
        return compile_cache.cached_jit(fn, key=key, example_args=ex)

    def _kernel_fn(self, step: SegStep, in_sds: Tuple) -> Callable:
        builder = _PANEL_KERNELS.get(step.kernel)
        if builder is None:
            raise KeyError(f"unregistered panel kernel {step.kernel!r}")
        sig = tuple((tuple(s.shape), str(s.dtype)) for s in in_sds)
        key = ("panel_kernel", step.kernel, sig, step.static)
        return compile_cache.cached_jit(builder(in_sds, step.static),
                                        key=key, example_args=in_sds)

    def _seg_walk(self, state, dispatch: bool):
        """Shared walker for :meth:`run_state_segmented` (dispatch=True,
        state = device arrays) and :meth:`prepare_segments`
        (dispatch=False, state = ShapeDtypeStructs — resolves/compiles
        every program without running, propagating carry shapes with
        eval_shape). One walker so warm-up and execution can never
        resolve different cache keys."""
        import jax
        state = dict(state)
        carries: Dict[str, Any] = {}
        i4 = (np.int32(0),) * 4
        for step in self.segments():
            ins = []
            for rd in step.reads:
                if rd.src == "carry":
                    ins.append(carries[rd.name])
                elif rd.src == "const":
                    v = np.asarray(rd.value)
                    ins.append(jax.ShapeDtypeStruct(v.shape, v.dtype)
                               if not dispatch else v)
                else:
                    D = state[rd.name]
                    D_sds = jax.ShapeDtypeStruct(D.shape, D.dtype)
                    v_sds = jax.ShapeDtypeStruct(
                        (rd.rows_b, rd.cols_b), D.dtype)
                    fn = self._window_fn(D_sds, v_sds, rd, "panel_extract")
                    if dispatch:
                        ins.append(fn(D, np.int32(rd.r0), np.int32(rd.c0),
                                      np.int32(rd.rows), np.int32(rd.cols)))
                    else:
                        ins.append(v_sds)
            in_sds = tuple(
                x if isinstance(x, jax.ShapeDtypeStruct) else
                jax.ShapeDtypeStruct(x.shape, x.dtype) for x in ins)
            kfn = self._kernel_fn(step, in_sds)
            if dispatch:
                outs = kfn(*ins)
            else:
                builder = _PANEL_KERNELS[step.kernel]
                outs = jax.eval_shape(builder(in_sds, step.static),
                                      *in_sds)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            if len(outs) != len(step.writes):
                raise ValueError(
                    f"panel kernel {step.kernel!r} returned {len(outs)} "
                    f"outputs for {len(step.writes)} writes")
            for wr, val in zip(step.writes, outs):
                if wr.dst == "carry":
                    carries[wr.name] = val
                    continue
                D = state[wr.name]
                D_sds = jax.ShapeDtypeStruct(D.shape, D.dtype)
                v_sds = jax.ShapeDtypeStruct(val.shape, val.dtype)
                fn = self._window_fn(D_sds, v_sds, wr, "panel_write")
                if dispatch:
                    state[wr.name] = fn(D, val, np.int32(wr.r0),
                                        np.int32(wr.c0), np.int32(wr.rows),
                                        np.int32(wr.cols))
                else:
                    state[wr.name] = D_sds     # shape unchanged
        return {name: state[name] for name in self.geoms}

    def run_state_segmented(self, state: Dict[str, Any]
                            ) -> Dict[str, Any]:
        """state → state through cached per-(kernel, bucket) programs
        dispatched wave-by-wave. Same collection-level results as
        :meth:`run_state`; compile cost bounded by distinct buckets
        (not waves) and shared across N, executors, and — with the
        persistent store — processes. JAX async dispatch pipelines the
        per-step calls."""
        return self._seg_walk(state, dispatch=True)

    def prepare_segments(self) -> int:
        """Resolve (compile or load) every program the segmented run
        will dispatch, without touching data — the serving warm-up.
        Returns the number of distinct cached programs in the walk."""
        n0 = compile_cache.jit_store_size()
        self._seg_walk(self.state_shapes(), dispatch=False)
        return compile_cache.jit_store_size() - n0

    # -- host-driven run --------------------------------------------------

    def run(self, jit: bool = True, segmented: bool = False) -> float:
        t0 = time.perf_counter()
        state = self.make_state()
        if segmented:
            out = self.run_state_segmented(state)
        else:
            fn = self.jitted if jit else self.run_state
            out = fn(state)
        for v in out.values():
            v.block_until_ready()
        dt = time.perf_counter() - t0
        self.write_back(out)
        return dt
