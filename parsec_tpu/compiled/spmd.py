"""SPMD distributed execution of wavefront plans over a device mesh.

Replaces the reference's remote-dep machinery (remote_dep.c /
remote_dep_mpi.c: activation AMs + rendezvous PUT/GET over MPI) for the
compiled path. The TPU-first recipe ("How to Scale Your Model"): pick a
``jax.sharding.Mesh``, annotate the stacked tile stores with a
``NamedSharding`` over the tile-slot dimension, and jit the store-passing
wavefront program over the mesh — XLA's SPMD partitioner inserts the
collectives (all-gathers / collective-permutes riding ICI) that the
reference implements by hand as activation trees + one-sided transfers.

Owner-computes refinement: distributed collections emit rank-grouped
slot orders (TiledMatrix.tile_index), so sharding the slot axis places
each tile's slot on (or near) its owner device and the partitioner's
collectives carry only true dataflow.

Preferential-pjit front end (compile-once serving)
--------------------------------------------------

:func:`compile_with_plan` is the single compilation entry for mesh
programs (the Titanax ``compile_step_with_plan`` helper shape):
explicit in/out shardings → a pjit-compiled program; a mesh without
shardings → a ``shard_map`` data-parallel fallback (the function must
then be shard-local — per-slot independent); neither → plain jit.
Whatever the branch, the product enters the same shared jit store and
persistent executor cache as the single-chip executors
(``utils/compile_cache.py``), keyed by mesh axes/devices + sharding
specs on top of the caller's key — so a serving process re-lowers a
mesh program exactly once per (program, mesh, sharding, shapes) and a
second process pays only deserialization.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..utils import compile_cache


def make_mesh(n_devices: Optional[int] = None, axis: str = "tiles"):
    """A 1D mesh over the first ``n_devices`` visible devices."""
    import jax
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.asarray(devs), (axis,))


def shard_stores(stores: Dict[str, Any], mesh, axis: str = "tiles"):
    """Place each stacked store sharded over its slot dimension (padding
    the slot count up to a multiple of the mesh size)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.devices.size
    sharding = NamedSharding(mesh, P(axis))
    out = {}
    for name, arr in stores.items():
        pad = (-arr.shape[0]) % n
        if pad:
            arr = jnp.concatenate(
                [arr, jnp.zeros((pad,) + arr.shape[1:], arr.dtype)], axis=0)
        out[name] = jax.device_put(arr, sharding)
    return out


# ---------------------------------------------------------------------------
# comm-mesh registry: the same-mesh detection of the device-direct data
# plane (comm.device_direct). When the runtime's comm ranks map onto the
# devices of ONE JAX mesh (the loopback fabric: one process, per-rank
# chips; a single-controller pod slice the same way), a dep between two
# ranks is an intra-mesh edge — the tile can move as an XLA device-to-
# device transfer (jax.device_put onto the consumer's device, riding
# ICI on real hardware) and only a control frame needs the wire.
# ---------------------------------------------------------------------------

_COMM_MESH = None


def register_comm_mesh(mesh, rank_devices=None) -> None:
    """Declare that comm rank ``r`` computes on ``rank_devices[r]``
    (default: the mesh's devices in flat order, round-robin). The
    device-direct path (``comm.device_direct=auto``) engages only once
    a mesh is registered — detection, not hope."""
    global _COMM_MESH
    devs = list(rank_devices) if rank_devices is not None \
        else list(mesh.devices.flat)
    _COMM_MESH = (mesh, devs)


def unregister_comm_mesh() -> None:
    global _COMM_MESH
    _COMM_MESH = None


def comm_mesh():
    """The registered ``(mesh, rank_devices)`` pair, or None."""
    return _COMM_MESH


def comm_mesh_device(rank: int):
    """The device comm rank ``rank`` computes on under the registered
    comm mesh, or None when no mesh is registered."""
    if _COMM_MESH is None:
        return None
    devs = _COMM_MESH[1]
    return devs[rank % len(devs)] if devs else None


def same_mesh(src_rank: int, dst_rank: int) -> bool:
    """Do both endpoints of a dep sit on one registered mesh whose
    devices this process can address (the device-direct eligibility
    test)? Multi-controller placements (a device owned by another
    process) route through the wire instead. Shares the locality
    predicate with the routing path (``device_plane.local_device``) so
    detection can never drift from what routing actually does."""
    from ..comm.device_plane import local_device
    return local_device(comm_mesh_device(src_rank)) and \
        local_device(comm_mesh_device(dst_rank))


def mesh_of_value(value):
    """The mesh a sharded value lives on (NamedSharding), or None —
    the collection-sharding detection hook: a runtime that stores its
    tiles mesh-sharded can register that mesh as the comm mesh."""
    sh = getattr(value, "sharding", None)
    mesh = getattr(sh, "mesh", None)
    return mesh


# ---------------------------------------------------------------------------
# preferential-pjit compilation helper
# ---------------------------------------------------------------------------

def _mesh_repr(mesh) -> Tuple:
    if mesh is None:
        return ()
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
            tuple(int(d.id) for d in mesh.devices.flat))


def _sharding_repr(s) -> Any:
    """Canonical key form of a sharding pytree (NamedShardings /
    PartitionSpecs / None leaves, possibly nested in dicts/tuples)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    def leaf(x):
        if x is None:
            return "none"
        if isinstance(x, NamedSharding):
            return ("named", _mesh_repr(x.mesh), tuple(repr(p)
                                                       for p in x.spec))
        if isinstance(x, PartitionSpec):
            return ("pspec", tuple(repr(p) for p in x))
        return repr(x)

    return jax.tree_util.tree_map(
        leaf, s, is_leaf=lambda x: x is None or
        isinstance(x, (NamedSharding, PartitionSpec)))


def compile_with_plan(fn: Callable, *, mesh=None, in_shardings=None,
                      out_shardings=None, in_specs=None, out_specs=None,
                      donate_argnums=(), example_args: Tuple = None,
                      key: Tuple = (), fn_key=None) -> Callable:
    """Compile ``fn`` for a device mesh, preferring ``pjit`` when the
    caller knows its shardings (SNIPPETS [2], Titanax
    ``compile_step_with_plan``):

    - ``in_shardings`` AND ``out_shardings`` given → pjit (``jax.jit``
      with shardings): XLA partitions the program, inserting the
      collectives true dataflow needs. Giving only one of the two is an
      error — a half-specified contract silently replicates the other
      side.
    - no shardings but a ``mesh`` → ``shard_map`` fallback for pure
      data-parallel map-style execution over ``in_specs``/``out_specs``
      (default: shard the leading axis of every argument over the
      mesh's first axis). ``fn`` must be shard-local.
    - neither → plain jit.

    Every branch enters the shared jit store / persistent executor
    cache keyed by (``fn``'s identity, caller key, branch, mesh,
    sharding specs) — a rebuilt front end for an already-served program
    never re-traces, and a second process deserializes instead of
    compiling. ``fn``'s identity defaults to its code fingerprint;
    pass ``fn_key`` when ``fn`` is a bound method / closure whose
    *instance state* shapes the trace (the fingerprint cannot see it)
    and the caller can name that state (e.g. a plan fingerprint).
    Functions that are neither stably fingerprintable nor covered by a
    caller ``fn_key`` are compiled directly and NOT cached — silent
    cross-function sharing (or pinning a per-request object graph in
    the never-evicted store) is worse than a re-trace.
    """
    import jax

    have_in = in_shardings is not None
    have_out = out_shardings is not None
    if have_in != have_out:
        raise ValueError(
            "compile_with_plan requires BOTH in_shardings and "
            "out_shardings when using pjit; pass neither to use the "
            "shard_map fallback")
    if fn_key is None:
        ok, fp = compile_cache.function_fingerprint(fn)
        if ok and getattr(fn, "__self__", None) is None:
            fn_key = ("fp", fp)
    shareable = fn_key is not None
    if have_in:
        wrapper = lambda f: jax.jit(               # noqa: E731
            f, in_shardings=in_shardings, out_shardings=out_shardings,
            donate_argnums=donate_argnums)
        if not shareable:
            return wrapper(fn)
        full_key = ("pjit", fn_key, key, _mesh_repr(mesh),
                    _sharding_repr(in_shardings),
                    _sharding_repr(out_shardings), tuple(donate_argnums)
                    if not isinstance(donate_argnums, int)
                    else donate_argnums)
        return compile_cache.cached_jit(
            fn, key=full_key, example_args=example_args,
            jit_wrapper=wrapper)
    if mesh is not None:
        from .ring_attention import _shard_map
        from jax.sharding import PartitionSpec as P
        sm = _shard_map()
        axis = mesh.axis_names[0]
        ispec = in_specs if in_specs is not None else P(axis)
        ospec = out_specs if out_specs is not None else P(axis)
        mapped = sm(fn, mesh=mesh, in_specs=ispec, out_specs=ospec)
        if not shareable:
            return jax.jit(mapped, donate_argnums=donate_argnums)
        full_key = ("shard_map", fn_key, key, _mesh_repr(mesh),
                    _sharding_repr(ispec), _sharding_repr(ospec))
        return compile_cache.cached_jit(
            mapped, key=full_key, example_args=example_args,
            donate_argnums=donate_argnums)
    if not shareable:
        return jax.jit(fn, donate_argnums=donate_argnums)
    return compile_cache.cached_jit(
        fn, key=("jit", fn_key, key), example_args=example_args,
        donate_argnums=donate_argnums)


def run_sharded(executor, mesh=None, n_devices: Optional[int] = None,
                axis: str = "tiles") -> Dict[str, Any]:
    """Execute the plan with mesh-sharded stores: one pjit-compiled XLA
    program for the whole DAG, collectives inserted by the partitioner.

    Goes through :func:`compile_with_plan` with explicit in/out
    ``NamedSharding``s (the preferential-pjit path), so the program
    lands in the shared/persistent executor cache keyed by (plan, mesh,
    shardings, shapes) and is reused across runs and processes.

    Returns the (unsharded, unpadded) result stores and writes tiles back
    to the plan's collections.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        mesh = make_mesh(n_devices, axis)
    stores = executor.make_stores()
    orig_sizes = {k: v.shape[0] for k, v in stores.items()}
    sharded = shard_stores(stores, mesh, axis)

    sharding = NamedSharding(mesh, P(axis))
    shardings = {name: sharding for name in sharded}
    sds = {name: jax.ShapeDtypeStruct(v.shape, v.dtype)
           for name, v in sharded.items()}
    from .wavefront import plan_structure_fingerprint
    ok, plan_fp = plan_structure_fingerprint(executor.plan)
    fps = sorted({executor._body_fp(grp.tc) or "unstable"
                  for wave in executor.plan.waves for grp in wave})
    stable = ok and "unstable" not in fps
    # run_arrays is a bound method: its trace depends on the plan, so
    # the fn identity is the plan+body fingerprint — and when THAT is
    # unstable, fn_key stays None and compile_with_plan compiles
    # without caching (a cached entry would pin the executor and its
    # tile data in the never-evicted store under a one-shot id key)
    fn = compile_with_plan(
        executor.run_arrays, mesh=mesh, in_shardings=(shardings,),
        out_shardings=shardings,
        example_args=(sds,) if stable else None,
        fn_key=("run_sharded", plan_fp, tuple(fps)) if stable else None)
    out = fn(sharded)
    for v in out.values():
        v.block_until_ready()
    clipped = {k: v[:orig_sizes[k]] for k, v in out.items()}
    for name, dc in executor.plan.collections.items():
        if dc.scratch:
            continue      # intra-DAG temporaries: no host write-back
        dc.from_stacked(clipped[name][:-1], executor.plan.slot_maps[name])
    return clipped
