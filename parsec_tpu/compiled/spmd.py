"""SPMD distributed execution of wavefront plans over a device mesh.

Replaces the reference's remote-dep machinery (remote_dep.c /
remote_dep_mpi.c: activation AMs + rendezvous PUT/GET over MPI) for the
compiled path. The TPU-first recipe ("How to Scale Your Model"): pick a
``jax.sharding.Mesh``, annotate the stacked tile stores with a
``NamedSharding`` over the tile-slot dimension, and jit the store-passing
wavefront program over the mesh — XLA's SPMD partitioner inserts the
collectives (all-gathers / collective-permutes riding ICI) that the
reference implements by hand as activation trees + one-sided transfers.

Owner-computes refinement: distributed collections emit rank-grouped
slot orders (TiledMatrix.tile_index), so sharding the slot axis places
each tile's slot on (or near) its owner device and the partitioner's
collectives carry only true dataflow.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def make_mesh(n_devices: Optional[int] = None, axis: str = "tiles"):
    """A 1D mesh over the first ``n_devices`` visible devices."""
    import jax
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.asarray(devs), (axis,))


def shard_stores(stores: Dict[str, Any], mesh, axis: str = "tiles"):
    """Place each stacked store sharded over its slot dimension (padding
    the slot count up to a multiple of the mesh size)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.devices.size
    sharding = NamedSharding(mesh, P(axis))
    out = {}
    for name, arr in stores.items():
        pad = (-arr.shape[0]) % n
        if pad:
            arr = jnp.concatenate(
                [arr, jnp.zeros((pad,) + arr.shape[1:], arr.dtype)], axis=0)
        out[name] = jax.device_put(arr, sharding)
    return out


def run_sharded(executor, mesh=None, n_devices: Optional[int] = None,
                axis: str = "tiles") -> Dict[str, Any]:
    """Execute the plan with mesh-sharded stores: one jitted XLA program
    for the whole DAG, collectives inserted by the partitioner.

    Returns the (unsharded, unpadded) result stores and writes tiles back
    to the plan's collections.
    """
    import jax

    if mesh is None:
        mesh = make_mesh(n_devices, axis)
    stores = executor.make_stores()
    orig_sizes = {k: v.shape[0] for k, v in stores.items()}
    sharded = shard_stores(stores, mesh, axis)
    fn = jax.jit(executor.run_arrays)
    out = fn(sharded)
    for v in out.values():
        v.block_until_ready()
    clipped = {k: v[:orig_sizes[k]] for k, v in out.items()}
    for name, dc in executor.plan.collections.items():
        if dc.scratch:
            continue      # intra-DAG temporaries: no host write-back
        dc.from_stacked(clipped[name][:-1], executor.plan.slot_maps[name])
    return clipped
