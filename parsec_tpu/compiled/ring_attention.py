"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The compiled XLA twins of the streaming-attention task DAG
(``parsec_tpu.algorithms.transformer``). Where the runtime form ships the
online-softmax state between tasks through activations (the reference's
chain-dataflow pattern, SURVEY §5 "long-context"), these shard the
sequence over a ``jax.sharding.Mesh`` axis and move KV blocks with XLA
collectives riding ICI:

- :func:`ring_attention` — each device holds one Q/K/V sequence block;
  KV blocks rotate around the ring with ``lax.ppermute`` while every
  device folds the visiting block into its online-softmax state
  (`Ring Attention with Blockwise Transformers`, Liu et al. 2023 —
  PAPERS.md). Peak memory per device is O(block²) independent of the
  full sequence length; the permute overlaps with the block compute.
- :func:`ulysses_attention` — all-to-all re-shard: scatter heads /
  gather sequence (`DeepSpeed-Ulysses`, Jacobs et al. 2023), dense
  per-head attention locally, inverse all-to-all back to
  sequence-sharded. One collective pair instead of N-1 permutes; needs
  n_heads divisible by the mesh axis size.

Both are pure jittable functions of sequence-sharded operands: drop them
under ``pjit``/``shard_map`` with the rest of a model and XLA fuses and
overlaps the collectives.
"""

from __future__ import annotations

import math
from typing import Optional


_MASKED = -1e30      # finite "minus infinity": keeps exp() NaN-free when
                     # an entire row is masked (fully-future KV blocks)


def _shard_map():
    """Version-portable ``shard_map``: top-level ``jax.shard_map``
    (JAX ≥ 0.6) with the ``check_vma`` kwarg, or the older
    ``jax.experimental.shard_map.shard_map`` whose equivalent kwarg is
    ``check_rep``. Returns a callable with the NEW signature; the
    ``check_vma`` kwarg is translated for old JAX."""
    import jax
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    from jax.experimental.shard_map import shard_map as sm_old

    def compat(f, *, mesh, in_specs, out_specs, check_vma=True):
        return sm_old(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)

    return compat


def _online_softmax_step(q_blk, k_cur, v_cur, acc, m, l, scale,
                         qpos=None, kpos=None):
    """One online-softmax fold. ``qpos``/``kpos``: global sequence
    positions of the query/key rows — when given, causal masking
    (key position ≤ query position) is applied."""
    import jax.numpy as jnp
    from ..ops.tile_kernels import matmul_precision

    s = jnp.matmul(q_blk, jnp.swapaxes(k_cur, -1, -2),
                   preferred_element_type=jnp.float32,
                   precision=matmul_precision()) * scale
    allowed = None
    if qpos is not None:
        allowed = qpos[:, None] >= kpos[None, :]
        s = jnp.where(allowed, s, _MASKED)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    if allowed is not None:
        # a fully-masked row would give m_new == _MASKED and p == 1 for
        # every masked entry (uniform attention over forbidden keys);
        # zeroing masked p makes the helper safe standalone even though
        # callers currently fold the resident diagonal block first and
        # skip fully-future blocks
        p = jnp.where(allowed, p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.matmul(
        p, v_cur, preferred_element_type=jnp.float32,
        precision=matmul_precision())
    return acc_new, m_new, l_new


def ring_attention(q, k, v, mesh, axis: str = "seq",
                   kv_chunk: Optional[int] = None,
                   causal: bool = False, impl: str = "xla"):
    """Multi-head attention with the sequence sharded over mesh ``axis``.

    ``q/k/v``: float arrays of shape ``(S, H, dh)`` (sequence-major) laid
    out ``PartitionSpec(axis)`` over ``mesh``. Returns the attention
    output in the same layout.

    ``kv_chunk``: fold each visiting KV block in chunks of this many
    keys (flash-attention-style inner loop) — peak score memory drops
    from O(Sb²) to O(Sb·kv_chunk) per head, which is what lets a single
    chip run long blocks. Must divide the per-device block length.

    ``causal``: apply causal masking over GLOBAL sequence positions —
    each device masks the visiting KV block against its query block's
    position range, so fully-future blocks contribute nothing while the
    ring still rotates uniformly.

    ``impl``: local-block computation. ``"xla"`` — the jnp online-
    softmax fold (works everywhere). ``"flash"`` — the pallas flash
    kernel (ops.flash_attention) per visiting KV block, partial results
    combined with the (o, lse) state merge; measured ~6× the xla fold
    at S=16384 on a v5e chip (the S×S score round-trips through HBM are
    what the kernel eliminates). ``kv_chunk`` maps to the kernel's key
    block size. The ppermute ring is identical in both modes.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    shard_map = _shard_map()
    if impl not in ("xla", "flash"):
        raise ValueError(f"ring_attention impl must be xla|flash: {impl!r}")

    n = mesh.shape[axis]
    scale = 1.0 / math.sqrt(q.shape[-1])
    perm = [(i, (i + 1) % n) for i in range(n)]

    if impl == "flash":
        from ..ops.flash_attention import (flash_attention,
                                           merge_attention_states)

        def block_flash(q_blk, k_blk, v_blk):
            my = lax.axis_index(axis)
            bk = min(kv_chunk, k_blk.shape[0]) if kv_chunk else 0

            def fold(k_cur, v_cur, diag):
                o, lse = flash_attention(
                    q_blk, k_cur, v_cur, causal=diag, scale=scale,
                    block_k=bk, return_lse=True)
                return o.astype(jnp.float32), lse

            # resident block first: with causal masking it is the
            # diagonal block (kernel-level causal mask); visiting blocks
            # are either fully past (unmasked) or fully future (skipped)
            o_c, lse_c = fold(k_blk, v_blk, causal)

            def step(carry, t):
                k_cur, v_cur, o_c, lse_c = carry
                k_cur = lax.ppermute(k_cur, axis, perm)
                v_cur = lax.ppermute(v_cur, axis, perm)
                kv_owner = (my - t - 1) % n

                def do_fold(op):
                    k_, v_, o1, l1 = op
                    o2, l2 = fold(k_, v_, False)
                    return merge_attention_states(o1, l1, o2, l2)

                if causal:
                    o_c, lse_c = lax.cond(
                        kv_owner < my, do_fold,
                        lambda op: (op[2], op[3]),
                        (k_cur, v_cur, o_c, lse_c))
                else:
                    o_c, lse_c = do_fold((k_cur, v_cur, o_c, lse_c))
                return (k_cur, v_cur, o_c, lse_c), None

            (k_f, v_f, o_c, lse_c), _ = lax.scan(
                step, (k_blk, v_blk, o_c, lse_c), jnp.arange(n - 1))
            return o_c.astype(q_blk.dtype)

        # check_vma=False: pallas_call's out_shape carries no varying-
        # across-mesh annotation, which the shard_map vma checker (JAX
        # ≥0.8) rejects; the kernel is per-device-local so the check
        # adds nothing here
        return shard_map(block_flash, mesh=mesh,
                         in_specs=(P(axis), P(axis), P(axis)),
                         out_specs=P(axis), check_vma=False)(q, k, v)

    def block(q_blk, k_blk, v_blk):
        # [Sb, H, dh] → head-major [H, Sb, dh] for batched matmuls
        qh = jnp.swapaxes(q_blk, 0, 1).astype(jnp.float32)
        kh = jnp.swapaxes(k_blk, 0, 1).astype(jnp.float32)
        vh = jnp.swapaxes(v_blk, 0, 1).astype(jnp.float32)
        Sb = qh.shape[1]
        my = lax.axis_index(axis)
        qpos = my * Sb + jnp.arange(Sb) if causal else None

        def fold_block(k_cur, v_cur, acc, m, l, kv_owner):
            # positions are always threaded; masking is keyed on qpos
            # (None in non-causal mode) so XLA DCEs the unused kpos
            kpos = kv_owner * Sb + jnp.arange(Sb)
            if kv_chunk is None or kv_chunk >= Sb:
                return _online_softmax_step(qh, k_cur, v_cur, acc, m, l,
                                            scale, qpos, kpos)
            if Sb % kv_chunk:
                raise ValueError(
                    f"kv_chunk={kv_chunk} must divide block length {Sb}")
            nch = Sb // kv_chunk
            # chunk axis leads so scan consumes chunks directly as xs
            kc = jnp.moveaxis(
                k_cur.reshape(k_cur.shape[0], nch, kv_chunk, -1), 1, 0)
            vc = jnp.moveaxis(
                v_cur.reshape(v_cur.shape[0], nch, kv_chunk, -1), 1, 0)

            def chunk_step(carry, xs):
                acc, m, l = carry
                kcur, vcur, kp = xs
                acc, m, l = _online_softmax_step(
                    qh, kcur, vcur, acc, m, l, scale, qpos, kp)
                return (acc, m, l), None

            (acc, m, l), _ = lax.scan(
                chunk_step, (acc, m, l),
                (kc, vc, kpos.reshape(nch, kv_chunk)))
            return acc, m, l

        def step(carry, t):
            # permute first, fold second: the local block is folded
            # before the loop, so exactly n-1 rotations happen — no
            # wasted final ppermute (XLA can't peel a scan iteration)
            k_cur, v_cur, acc, m, l = carry
            k_cur = lax.ppermute(k_cur, axis, perm)
            v_cur = lax.ppermute(v_cur, axis, perm)
            # after t+1 rotations, the resident block came from rank
            # (my - t - 1) mod n — its global positions drive the mask
            kv_owner = (my - t - 1) % n
            if causal:
                # fully-future blocks contribute nothing: skip their fold
                # (local compute only — the ppermute above stays uniform
                # across devices, so the ring itself is unaffected)
                acc, m, l = lax.cond(
                    kv_owner <= my,
                    lambda op: fold_block(*op),
                    lambda op: (op[2], op[3], op[4]),
                    (k_cur, v_cur, acc, m, l, kv_owner))
            else:
                acc, m, l = fold_block(k_cur, v_cur, acc, m, l, kv_owner)
            return (k_cur, v_cur, acc, m, l), None

        # fold the resident block, then rotate n-1 times; the init state
        # derives from qh so it carries the same varying manual axes as
        # the loop outputs (JAX ≥0.8 shard_map typing)
        acc0, m0, l0 = fold_block(
            kh, vh, qh * 0.0, qh[..., 0] * 0.0 - jnp.inf,
            qh[..., 0] * 0.0, my)
        (k_f, v_f, acc, m, l), _ = lax.scan(
            step, (kh, vh, acc0, m0, l0), jnp.arange(n - 1))
        out = acc / l[..., None]
        return jnp.swapaxes(out, 0, 1).astype(q_blk.dtype)

    fn = shard_map(block, mesh=mesh,
                   in_specs=(P(axis), P(axis), P(axis)),
                   out_specs=P(axis))
    return fn(q, k, v)


def ulysses_attention(q, k, v, mesh, axis: str = "seq"):
    """All-to-all sequence parallelism: re-shard (S/n, H, dh) →
    (S, H/n, dh), dense per-head attention locally, inverse all-to-all.
    ``H`` must be divisible by the mesh axis size."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    shard_map = _shard_map()

    n = mesh.shape[axis]
    H = q.shape[1]
    if H % n:
        raise ValueError(f"n_heads={H} not divisible by mesh axis size {n}")
    scale = 1.0 / math.sqrt(q.shape[-1])

    def block(q_blk, k_blk, v_blk):
        # scatter heads, gather sequence: [Sb, H, dh] → [Sb·n, H/n, dh]
        def fwd(x):
            x = lax.all_to_all(x, axis, split_axis=1, concat_axis=0,
                               tiled=True)
            return jnp.swapaxes(x, 0, 1).astype(jnp.float32)  # [H/n, S, dh]

        from ..ops.tile_kernels import matmul_precision
        qh, kh, vh = fwd(q_blk), fwd(k_blk), fwd(v_blk)
        s = jnp.matmul(qh, jnp.swapaxes(kh, -1, -2),
                       preferred_element_type=jnp.float32,
                       precision=matmul_precision()) * scale
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.matmul(p, vh, preferred_element_type=jnp.float32,
                         precision=matmul_precision())
        # inverse: gather heads, scatter sequence
        out = jnp.swapaxes(out, 0, 1)                         # [S, H/n, dh]
        out = lax.all_to_all(out, axis, split_axis=0, concat_axis=1,
                             tiled=True)
        return out.astype(q_blk.dtype)

    fn = shard_map(block, mesh=mesh,
                   in_specs=(P(axis), P(axis), P(axis)),
                   out_specs=P(axis))
    return fn(q, k, v)


def dense_attention(q, k, v, causal: bool = False):
    """Unsharded reference: softmax(QKᵀ/√dh)·V per head; q/k/v (S, H, dh)."""
    import jax
    import jax.numpy as jnp

    S = q.shape[0]
    from ..ops.tile_kernels import matmul_precision
    scale = 1.0 / math.sqrt(q.shape[-1])
    qh = jnp.swapaxes(q, 0, 1).astype(jnp.float32)
    kh = jnp.swapaxes(k, 0, 1).astype(jnp.float32)
    vh = jnp.swapaxes(v, 0, 1).astype(jnp.float32)
    s = jnp.matmul(qh, jnp.swapaxes(kh, -1, -2),
                   preferred_element_type=jnp.float32,
                   precision=matmul_precision()) * scale
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((S, S), bool)), s, _MASKED)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.matmul(p, vh, preferred_element_type=jnp.float32,
                     precision=matmul_precision())
    return jnp.swapaxes(out, 0, 1).astype(q.dtype)
