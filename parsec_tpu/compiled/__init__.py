"""Compiled execution paths (the TPU performance layer).

The reference dispatches every task individually through a scheduler +
device stream pipeline. On TPU, per-task dispatch cannot feed the MXU —
launch overhead dominates for tile-sized kernels. This package compiles a
PTG taskpool's whole DAG into XLA programs instead:

- :mod:`wavefront`: enumerate the (closed-form) task space, level it into
  waves, batch same-class tasks per wave, and execute each (class, wave)
  group as one vmapped XLA call gathering/scattering tiles from a stacked
  HBM-resident tile store. Single-chip performance path.
- :mod:`spmd`: the same wavefront plan sharded over a jax.sharding.Mesh —
  owner-computes over block-cyclic collections with XLA collectives
  carrying inter-rank dependencies over ICI (replaces remote_dep_mpi.c);
  its :func:`~spmd.compile_with_plan` is the preferential-pjit
  compilation front end every mesh program goes through.
- :mod:`panels`: wave-fused dense lowering (the flagship form) with the
  compile-once segmented path — bucketed shape-polymorphic panel
  kernels shared across N, executors, and processes via the persistent
  executor cache (utils/compile_cache.py).
"""

from .wavefront import (WavefrontPlan, plan_taskpool, WavefrontExecutor,
                        plan_structure_fingerprint)
from .panels import PanelExecutor, bucket_tiles
from . import spmd
from .spmd import compile_with_plan
from .ring_attention import (ring_attention, ulysses_attention,
                             dense_attention)
