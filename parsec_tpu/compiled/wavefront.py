"""Wavefront compiler: PTG DAG → batched XLA execution.

Why this exists: the reference keeps the MXU-equivalent (CUDA cores) busy
by pipelining *individual* tile tasks through streams
(device_cuda_module.c pipeline). On TPU, per-task dispatch of tile-sized
kernels cannot reach a useful fraction of peak — launch + gap overheads
dominate and XLA can't fuse across dispatches. The TPU-idiomatic execution
of a task DAG is:

1. enumerate the task space (closed-form, from the PTG description);
2. level the DAG into *waves* (all tasks whose predecessors completed in
   earlier waves) — host-side topological leveling;
3. inside a wave, group tasks by task class and execute each group as ONE
   vmapped XLA call: gather the group's input tiles from a stacked
   HBM-resident store (one (ntiles, mb, nb) jax.Array per collection),
   run the batched body (a single large batched matmul for GEMM-like
   classes → MXU-friendly), scatter outputs back;
4. the whole schedule is a pure function ``stores → stores``, so it can be
   jitted end-to-end (one XLA program for the whole DAG) or dispatched
   wave-by-wave with power-of-two batch bucketing to bound compilation.

Store-based execution is valid when every intermediate tile version has
its readers ordered (by wave level) before the next writer of that tile —
true for accumulate-chain dense LA DAGs (POTRF/GEMM/QR). ``plan_taskpool``
verifies this *hazard-freedom* property while planning and rejects DAGs
that need value-passing (those run on the host runtime instead).
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.task import DeviceType, FlowAccess, Task
from ..core.taskpool import DataRef
from ..dsl.ptg import PTGTaskClass, Taskpool as PTGTaskpool
from ..utils import compile_cache
from ..utils.debug import debug_verbose


@dataclass
class WaveGroup:
    """All tasks of one class inside one wave (sub-grouped by reshape
    signature when dep ``[type=...]`` specs differ across instances)."""
    tc: PTGTaskClass
    level: int
    tasks: List[Tuple[int, ...]]
    # per non-CTL flow, (collection name, np.int32[B] tile-slot indices)
    in_slots: List[Tuple[str, np.ndarray]] = field(default_factory=list)
    out_slots: List[Tuple[str, np.ndarray]] = field(default_factory=list)
    # per in-flow composed ReshapeSpec (or None), shared by every task
    # in the group — applied to the gathered stack before the body
    in_specs: List[Optional[Any]] = field(default_factory=list)


@dataclass
class WavefrontPlan:
    taskpool: PTGTaskpool
    waves: List[List[WaveGroup]]
    collections: Dict[str, Any]              # name -> collection
    slot_maps: Dict[str, Dict[Tuple, int]]   # name -> (tile key -> slot)
    n_tasks: int = 0
    # True when some non-CTL flow carries task->task values with no tile
    # placement: only executors that keep values in carry state (the
    # panel-fused path) or the host runtime can run such plans
    has_value_flows: bool = False
    # dep [type=...] support: True when any dep declares a ReshapeSpec
    has_reshapes: bool = False
    # (collection name, slot) -> spec of the LAST terminal data write —
    # applied by write_back (the Out-side conversion of DataRef writes)
    terminal_specs: Dict[Tuple[str, int], Any] = field(default_factory=dict)

    @property
    def n_waves(self) -> int:
        return len(self.waves)


def plan_structure_fingerprint(plan: "WavefrontPlan"
                               ) -> Tuple[bool, str]:
    """``(stable, digest)`` over everything of a plan that shapes a
    traced program: collection geometry/dtypes, the full wave/group
    structure with slot indices, and reshape-spec identities. Equal
    digests ⇒ equal traces (given equal bodies/fusers, fingerprinted
    separately) — the key that lets rebuilt executors share jitted
    callables instead of re-tracing per function object."""
    h = hashlib.sha256()
    stable = True
    for name in sorted(plan.collections):
        dc = plan.collections[name]
        h.update(repr((name, dc.mb, dc.nb, dc.mt, dc.nt,
                       str(np.dtype(dc.dtype)),
                       bool(getattr(dc, "scratch", False)))).encode())
    for w, wave in enumerate(plan.waves):
        for grp in wave:
            h.update(repr((w, grp.tc.name, tuple(grp.tasks))).encode())
            for (nm, idx) in grp.in_slots:
                h.update(nm.encode())
                h.update(np.ascontiguousarray(idx).tobytes())
            for (nm, idx) in grp.out_slots:
                h.update(nm.encode())
                h.update(np.ascontiguousarray(idx).tobytes())
            for s in grp.in_specs:
                if s is None:
                    h.update(b"nospec")
                    continue
                h.update(repr(getattr(s, "key", None)).encode())
                ok, fp = compile_cache.function_fingerprint(s.fn)
                stable = stable and ok
                h.update(fp.encode())
    h.update(repr((plan.n_tasks, plan.has_value_flows,
                   plan.has_reshapes)).encode())
    return stable, h.hexdigest()


def class_body_fingerprint(tc: PTGTaskClass, device_type: DeviceType
                           ) -> Tuple[bool, str]:
    """``(stable, digest)`` of the bodies a compiled executor may trace
    for ``tc``: the chore hook plus its batched reformulations."""
    chore = tc.chore_for(device_type) or tc.chore_for(DeviceType.CPU)
    if chore is None:
        return False, f"nobody:{tc.name}"
    parts, stable = [tc.name], True
    for fn in (chore.hook, chore.batch_hook, chore.batch_body):
        if fn is None:
            parts.append("none")
            continue
        ok, fp = compile_cache.function_fingerprint(fn)
        stable = stable and ok
        parts.append(fp)
    parts.append(repr(tuple(getattr(chore, "batch_hook_shared", None)
                            or ())))
    return stable, hashlib.sha256(
        "\x00".join(parts).encode()).hexdigest()


def _flow_tile(tc: PTGTaskClass, fname: str, locals) -> Tuple[Any, Tuple]:
    spec = tc.specs[fname]
    if spec.tile is None:
        raise ValueError(
            f"compiled mode requires FlowSpec.tile on {tc.name}.{fname}")
    dc, key = spec.tile(tc.tp.g, *locals)
    return dc, tuple(key)


def _is_value_flow(tc: PTGTaskClass, f) -> bool:
    """Non-CTL flow with no tile placement: a task->task value (e.g. a
    whole factored panel) that never lives in a collection. Such flows
    still level the DAG (their edges order waves) but have no slots; the
    per-tile executors cannot feed them — wave fusers carry them in
    state, the host runtime passes them with activations."""
    return (not f.is_ctl) and tc.specs[f.name].tile is None


def plan_taskpool(tp: PTGTaskpool) -> WavefrontPlan:
    """Enumerate, level, group and hazard-check a PTG taskpool.

    Dep ``[type=...]`` reshape specs (parsec_reshape.c analog) are
    static per-edge layout maps, so the planner resolves them up front:
    each consumer's composed (Out ∘ In) spec is recorded per group and
    applied to the gathered stack at execution (XLA fuses the cast/
    transpose into the body); terminal DataRef specs are applied by
    write_back. Groups whose instances disagree on specs are split."""
    from ..dsl.ptg import taskpool_uses_reshape
    has_reshapes = taskpool_uses_reshape(tp)
    # ---- enumerate tasks and assign ids
    tasks: List[Tuple[PTGTaskClass, Tuple[int, ...]]] = []
    tid: Dict[Tuple[str, Tuple], int] = {}
    for tc in tp.task_classes:
        for p in tc.enumerate_space():
            tid[(tc.name, p)] = len(tasks)
            tasks.append((tc, p))
    n = len(tasks)

    # ---- build successor edges via the closed-form iterators
    succs: List[List[int]] = [[] for _ in range(n)]
    edges: List[Tuple[int, int, str]] = []   # (producer, consumer, flow)
    # (consumer tid, flow) -> composed producer∘consumer ReshapeSpec
    # (None recorded for spec-less edges so mixed spec/no-spec fan-ins
    # are detectable; consumers treat stored-None as missing)
    edge_specs: Dict[Tuple[int, str], Any] = {}
    _NO_SPEC = object()
    indeg = np.zeros(n, dtype=np.int64)
    for i, (tc, p) in enumerate(tasks):
        dry = Task(tp, tc, p)
        for f in tc.flows:
            dry.data[f.name] = 0
            dry.output[f.name] = 0
        for ref in tc.iterate_successors(dry):
            if isinstance(ref, DataRef):
                continue
            j = tid[(ref.task_class.name, tuple(ref.locals))]
            succs[i].append(j)
            edges.append((i, j, ref.flow_name))
            # conflicting per-(consumer, flow) reshape specs — including
            # a reshaped edge mixed with an unreshaped one — would
            # silently apply one edge's spec to every gathered operand;
            # detect at plan time and direct such DAGs to the host
            # runtime (which applies specs per edge)
            prev = edge_specs.get((j, ref.flow_name), _NO_SPEC)
            # identity = (name, fn): name alone would let two same-named
            # specs with DIFFERENT fns through, silently applying one
            # edge's fn to both gathered operands — the exact
            # misconversion this guard exists to reject
            new_id = ((ref.reshape_spec.name, ref.reshape_spec.fn)
                      if ref.reshape_spec is not None else None)
            if prev is not _NO_SPEC:
                prev_id = ((prev.name, prev.fn)
                           if prev is not None else None)
                if prev_id != new_id:
                    ctc, cp = tasks[j]
                    pn = prev.name if prev is not None else None
                    nn = (ref.reshape_spec.name
                          if ref.reshape_spec is not None else None)
                    what = (f"same name {pn!r} but different fn objects "
                            "(share ONE ReshapeSpec instance across "
                            "edges when the conversion is the same)"
                            if pn == nn else f"{pn!r} vs {nn!r}")
                    raise ValueError(
                        f"task {ctc.name}{cp} flow {ref.flow_name!r} "
                        f"receives conflicting reshape specs ({what}) "
                        "on different incoming edges; the compiled "
                        "executors apply one spec per gathered flow — "
                        "run this taskpool on the host runtime")
            edge_specs[(j, ref.flow_name)] = ref.reshape_spec
            indeg[j] += 1

    # ---- Kahn leveling (batched in the C++ core when available)
    from .. import _native
    native_levels = None
    if n and _native.available():
        try:
            native_levels = _native.kahn_levels(
                n, [(i, j) for (i, j, _f) in edges])
        except RuntimeError as exc:
            raise RuntimeError(f"PTG DAG has a cycle: {exc}") from exc
    if native_levels is not None:
        level = np.asarray(native_levels, dtype=np.int64)
    else:
        level = np.zeros(n, dtype=np.int64)
        frontier = [i for i in range(n) if indeg[i] == 0]
        seen = len(frontier)
        while frontier:
            nxt = []
            for i in frontier:
                for j in succs[i]:
                    level[j] = max(level[j], level[i] + 1)
                    indeg[j] -= 1
                    if indeg[j] == 0:
                        nxt.append(j)
                        seen += 1
            frontier = nxt
        if seen != n:
            raise RuntimeError("PTG DAG has a cycle")

    # ---- per-task input reshape specs (static, from the closed form)
    def _in_flows(tc: PTGTaskClass):
        return [f for f in tc.flows if not f.is_ctl
                and not _is_value_flow(tc, f)
                and (f.access & FlowAccess.READ)]

    def _task_in_specs(i: int, tc: PTGTaskClass, p) -> Tuple:
        if not has_reshapes:
            return ()
        specs = []
        for f in _in_flows(tc):
            spec = edge_specs.get((i, f.name))
            if spec is None:
                dep = tc._active_in(tp.g, tc.specs[f.name], p)
                if dep is not None and dep.src is None and \
                        dep.reshape is not None:
                    spec = dep.reshape
            specs.append(spec)
        return tuple(specs)

    task_specs: List[Tuple] = [
        _task_in_specs(i, tc, p) for i, (tc, p) in enumerate(tasks)]

    # ---- group into waves (split by reshape signature: one group =
    # one batched body call, so every instance must share its specs)
    n_waves = int(level.max()) + 1 if n else 0
    waves: List[List[WaveGroup]] = [[] for _ in range(n_waves)]
    groups: Dict[Tuple, WaveGroup] = {}
    for i, (tc, p) in enumerate(tasks):
        sig = tuple(s.key if s is not None else None
                    for s in task_specs[i])
        gkey = (int(level[i]), tc.name, sig)
        grp = groups.get(gkey)
        if grp is None:
            grp = WaveGroup(tc=tc, level=int(level[i]), tasks=[],
                            in_specs=list(task_specs[i]) or
                            [None] * len(_in_flows(tc)))
            groups[gkey] = grp
            waves[int(level[i])].append(grp)
        grp.tasks.append(p)

    # ---- collect collections + slot maps; hazard check
    collections: Dict[str, Any] = {}
    slot_maps: Dict[str, Dict[Tuple, int]] = {}

    def _register(dc) -> str:
        if dc.name not in collections:
            collections[dc.name] = dc
            slot_maps[dc.name] = dc.tile_index()
        elif collections[dc.name] is not dc:
            raise ValueError(f"two collections share the name {dc.name!r}")
        return dc.name

    has_value_flows = any(
        _is_value_flow(tc, f)
        for tc in tp.task_classes for f in tc.flows)
    for w, wave in enumerate(waves):
        for grp in wave:
            tc = grp.tc
            in_fl = [f for f in tc.flows if not f.is_ctl
                     and not _is_value_flow(tc, f)
                     and (f.access & FlowAccess.READ)]
            out_fl = [f for f in tc.flows if not f.is_ctl
                      and not _is_value_flow(tc, f)
                      and (f.access & FlowAccess.WRITE)]
            ins: Dict[str, List[int]] = {f.name: [] for f in in_fl}
            outs: Dict[str, List[int]] = {f.name: [] for f in out_fl}
            in_names: Dict[str, str] = {}
            out_names: Dict[str, str] = {}
            for p in grp.tasks:
                for f in in_fl:
                    dc, key = _flow_tile(tc, f.name, p)
                    name = _register(dc)
                    in_names[f.name] = name
                    ins[f.name].append(slot_maps[name][key])
                for f in out_fl:
                    dc, key = _flow_tile(tc, f.name, p)
                    name = _register(dc)
                    out_names[f.name] = name
                    outs[f.name].append(slot_maps[name][key])
            grp.in_slots = [(in_names[f.name],
                             np.asarray(ins[f.name], dtype=np.int32))
                            for f in in_fl]
            grp.out_slots = [(out_names[f.name],
                              np.asarray(outs[f.name], dtype=np.int32))
                             for f in out_fl]

    # ---- hazard checks for store-based execution
    # (a) a tile must not be written twice in one wave (lost update);
    # (b) for every dataflow edge P --tile T--> R, no OTHER task may write
    #     T in a wave w with level(P) < w < level(R): the store would hand
    #     R a newer version than the dataflow prescribes. Same-wave writes
    #     (w == level(R)) are safe — the wave gathers before it scatters.
    write_waves: Dict[Tuple[str, Tuple], List[int]] = {}
    for w, wave in enumerate(waves):
        for grp in wave:
            for p in grp.tasks:
                for f in grp.tc.flows:
                    if f.is_ctl or not (f.access & FlowAccess.WRITE) \
                            or _is_value_flow(grp.tc, f):
                        continue
                    dc, key = _flow_tile(grp.tc, f.name, p)
                    tk = (dc.name, key)
                    lst = write_waves.setdefault(tk, [])
                    if w in lst:
                        raise RuntimeError(
                            f"tile {tk} written twice in wave {w}: DAG "
                            f"under-constrained for store-based execution")
                    lst.append(w)
    for (i, j, fname) in edges:
        tc_j, p_j = tasks[j]
        f_j = tc_j.flow_by_name[fname]
        if f_j.is_ctl or _is_value_flow(tc_j, f_j):
            continue
        dc, key = _flow_tile(tc_j, fname, p_j)
        lw, lr = int(level[i]), int(level[j])
        for w in write_waves.get((dc.name, key), ()):
            if lw < w < lr:
                tc_i, p_i = tasks[i]
                raise RuntimeError(
                    f"WAR/versioning hazard on tile {(dc.name, key)}: "
                    f"{tc_i.name}{p_i}@wave{lw} feeds {tc_j.name}{p_j}@"
                    f"wave{lr} but the tile is rewritten in wave {w}; "
                    f"use the host runtime for this DAG")

    # ---- terminal DataRef reshape specs (Out-side [type=...]): applied
    # once by write_back, matching the host runtime's per-write
    # conversion for the FINAL value. A reshaped write that a LATER
    # data-sourced read would observe has no store representation (the
    # store keeps raw values) — refuse loudly.
    terminal_specs: Dict[Tuple[str, int], Any] = {}
    if has_reshapes:
        term_wave: Dict[Tuple[str, int], int] = {}
        reshaped_wmin: Dict[Tuple[str, int], int] = {}
        data_read_wave: Dict[Tuple[str, int], int] = {}
        g = tp.g
        for i, (tc, p) in enumerate(tasks):
            w = int(level[i])
            for spec_ in tc.spec_list:
                for dep in spec_.outs:
                    if dep.data is None or not dep.active(g, p):
                        continue
                    dc, key = dep.data(g, *p)
                    slot_key = (dc.name, slot_maps[dc.name][tuple(key)])
                    if dep.reshape is not None:
                        reshaped_wmin[slot_key] = min(
                            reshaped_wmin.get(slot_key, 1 << 30), w)
                        if term_wave.get(slot_key, -1) <= w:
                            terminal_specs[slot_key] = dep.reshape
                            term_wave[slot_key] = w
                    elif term_wave.get(slot_key, -1) <= w:
                        terminal_specs.pop(slot_key, None)
                        term_wave[slot_key] = w
                dep = tc._active_in(g, spec_, p)
                if dep is not None and dep.data is not None and \
                        spec_.tile is not None:
                    dc, key = dep.data(g, *p)
                    slot_key = (dc.name, slot_maps[dc.name][tuple(key)])
                    data_read_wave[slot_key] = max(
                        data_read_wave.get(slot_key, -1), w)
        for slot_key, w_r in reshaped_wmin.items():
            if data_read_wave.get(slot_key, -1) > w_r:
                raise NotImplementedError(
                    f"tile {slot_key} is written with an Out-side "
                    f"reshape and read back from the collection in a "
                    f"later wave; store-based execution keeps raw "
                    f"values — run this taskpool on the host runtime")

    plan = WavefrontPlan(taskpool=tp, waves=waves, collections=collections,
                         slot_maps=slot_maps, n_tasks=n,
                         has_value_flows=has_value_flows,
                         has_reshapes=has_reshapes,
                         terminal_specs=terminal_specs)
    debug_verbose(3, "wavefront", "planned %s: %d tasks, %d waves",
                  tp.name, n, len(waves))
    return plan


class WavefrontExecutor:
    """Executes a :class:`WavefrontPlan` on the TPU.

    Two executable forms, both pure and jittable end-to-end:
    - :meth:`run_tile_dict` — every tile its own array; preferred
      single-chip form (no per-wave full-store copies; used by bench).
    - :meth:`run_arrays` — stacked ``{name: store}`` form; the input to
      the SPMD mesh path (sharded along the slot axis; used by
      __graft_entry__ and compiled.spmd).
    - :meth:`run` — host-driven wrapper: collections → stacked stores →
      ``run_arrays`` → write back.

    Batch padding: every group's gather/scatter indices are padded to the
    next power of two; scatter padding lands in a dummy slot appended to
    each store, so bucketized compilation reuses a handful of shapes per
    class instead of one per wave.
    """

    def __init__(self, plan: WavefrontPlan, bucket: bool = True,
                 device_type: DeviceType = DeviceType.TPU):
        import jax
        import jax.numpy as jnp
        if getattr(plan.taskpool, "requires_fuser", False):
            raise ValueError(
                f"taskpool {plan.taskpool.name!r} has bodies that read "
                "the collection directly (CTL-gather pattern); per-tile "
                "compiled execution cannot feed them — use the "
                "PanelExecutor (compiled.panels) or the host runtime")
        if plan.has_value_flows:
            raise ValueError(
                f"taskpool {plan.taskpool.name!r} carries task->task "
                "values with no tile placement; per-tile compiled "
                "execution cannot route them — use the PanelExecutor "
                "(wave fusers keep values in carry state) or the host "
                "runtime")
        self.jax, self.jnp = jax, jnp
        self.plan = plan
        self.bucket = bucket
        self.device_type = device_type
        self._vmapped: Dict[str, Callable] = {}
        self._segments: Dict[Tuple, Callable] = {}
        # body fingerprints (per class, memoized): the segment/whole-DAG
        # caches are shared through the module-level keyed store in
        # compile_cache — jit caches by FUNCTION OBJECT, so the old
        # per-instance jax.jit wrappers re-traced the same programs on
        # every executor rebuilt from an equal plan. Classes whose
        # bodies have no stable fingerprint fall back to per-instance
        # caching (never to silent cross-instance sharing).
        self._body_fps: Dict[str, Optional[str]] = {}
        self._plan_fp: Optional[str] = None
        self._jitted = None

    def _body_fp(self, tc: PTGTaskClass) -> Optional[str]:
        fp = self._body_fps.get(tc.name, "")
        if fp == "":
            ok, digest = class_body_fingerprint(tc, self.device_type)
            fp = digest if ok else None
            self._body_fps[tc.name] = fp
        return fp

    @property
    def jitted(self) -> Callable:
        """The whole-DAG jitted ``run_arrays`` — shared across
        executors built from structurally-equal plans (and persisted
        when the executor store is enabled), keyed by the plan
        fingerprint + every class's body fingerprint + store shapes."""
        if self._jitted is not None:
            return self._jitted
        if self._plan_fp is None:
            ok, digest = plan_structure_fingerprint(self.plan)
            self._plan_fp = digest if ok else None
        fps = [self._body_fp(grp.tc) for wave in self.plan.waves
               for grp in wave]
        if self._plan_fp is None or any(f is None for f in fps):
            self._jitted = self.jax.jit(self.run_arrays)
            return self._jitted
        import jax
        shapes = tuple(sorted(
            (name, len(self.plan.slot_maps[name]) + 1, dc.mb, dc.nb,
             str(np.dtype(dc.dtype)))
            for name, dc in self.plan.collections.items()))
        sds = {name: jax.ShapeDtypeStruct(
            (len(self.plan.slot_maps[name]) + 1, dc.mb, dc.nb),
            np.dtype(dc.dtype))
            for name, dc in self.plan.collections.items()}
        key = ("wf_monolith", self._plan_fp, tuple(sorted(set(fps))),
               shapes, self.bucket, self.device_type.name)
        self._jitted = compile_cache.cached_jit(
            self.run_arrays, key=key, example_args=(sds,))
        return self._jitted

    # -- body lookup ------------------------------------------------------
    def _raw_body(self, tc: PTGTaskClass) -> Callable:
        """The host body adapted to the executor's calling convention:
        the executor gathers only READ flows, while host bodies take
        every non-CTL flow in declaration order (WRITE-only flows are
        placeholder arguments) — rebuild the full argument list with
        None in the WRITE-only slots."""
        chore = tc.chore_for(self.device_type) or \
            tc.chore_for(DeviceType.CPU)
        if chore is None:
            raise ValueError(f"no body for {tc.name}")
        body = chore.hook
        nonctl = [f for f in tc.flows if not f.is_ctl]
        if all(f.access & FlowAccess.READ for f in nonctl):
            return body
        reads = [bool(f.access & FlowAccess.READ) for f in nonctl]

        def adapted(task, *read_vals, _b=body, _reads=tuple(reads)):
            it = iter(read_vals)
            args = [next(it) if r else None for r in _reads]
            return _b(task, *args)

        return adapted

    def _chore(self, tc: PTGTaskClass):
        return tc.chore_for(self.device_type) or tc.chore_for(DeviceType.CPU)

    def _hook_applies(self, chore, grp: WaveGroup) -> bool:
        """A batch_hook may assume flows named in ``batch_hook_shared``
        hold ONE tile across the whole group (e.g. the shared triangular
        factor of a TRSM wave). Verify that from the planner's slot
        indices — host-side, once per group — and fall back to vmap when
        the grouping breaks the assumption (future leveling changes must
        not silently mis-apply the hook)."""
        if chore is None or chore.batch_hook is None:
            return False
        shared = getattr(chore, "batch_hook_shared", None) or ()
        if not shared:
            return True
        in_fl = [f for f in grp.tc.flows
                 if not f.is_ctl and (f.access & FlowAccess.READ)]
        by_name = {f.name: slots for f, (_n, slots) in
                   zip(in_fl, grp.in_slots)}
        return all(len(np.unique(by_name[name])) == 1
                   for name in shared if name in by_name)

    def _body(self, tc: PTGTaskClass, batch: int,
              grp: Optional[WaveGroup] = None) -> Callable:
        """Batched body. Preference order: the chore's hand-written
        ``batch_hook`` (class-specific batched reformulation, guarded by
        its shared-flow assumption), then the batch == 1 unvmapped fast
        path (batched cholesky/triangular-solve lower poorly on TPU and
        diagonal-panel groups are singletons on the critical path), then
        mechanical vmap."""
        chore = self._chore(tc)
        if grp is not None and self._hook_applies(chore, grp):
            # raw hook: _exec_group normalizes every body's outputs
            return chore.batch_hook
        if batch == 1:
            fn = self._vmapped.get((tc.name, 1))
            if fn is None:
                body = self._raw_body(tc)

                def one(*tiles, _b=body, _tc=tc):
                    outs = self._normalize_outs(
                        _tc, _b(None, *(t[0] for t in tiles)))
                    return tuple(o[None] for o in outs)

                fn = one
                self._vmapped[(tc.name, 1)] = fn
            return fn
        fn = self._vmapped.get(tc.name)
        if fn is None:
            body = self._raw_body(tc)
            fn = self.jax.vmap(lambda *tiles, _b=body: _b(None, *tiles))
            self._vmapped[tc.name] = fn
        return fn

    @staticmethod
    def _pad(idx: np.ndarray, size: int, fill: int) -> np.ndarray:
        if len(idx) == size:
            return idx
        out = np.full(size, fill, dtype=np.int32)
        out[:len(idx)] = idx
        return out

    @staticmethod
    def _normalize_outs(tc: PTGTaskClass, outs) -> tuple:
        """Body returns → tuple ordered by WRITE-flow declaration order.
        Bodies may return a dict keyed by flow name (the host runtime
        convention), a tuple/list, or a single value."""
        out_fl = [f for f in tc.flows
                  if not f.is_ctl and (f.access & FlowAccess.WRITE)]
        if isinstance(outs, dict):
            missing = [f.name for f in out_fl if f.name not in outs]
            if missing:
                raise ValueError(
                    f"{tc.name}: body dict missing outputs {missing}")
            return tuple(outs[f.name] for f in out_fl)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        if len(outs) != len(out_fl):
            raise ValueError(
                f"{tc.name}: body returned {len(outs)} outputs "
                f"for {len(out_fl)} write flows")
        return tuple(outs)

    def _exec_group(self, grp: WaveGroup, batch: int,
                    inputs: List[Any]) -> List[Any]:
        """Run one wave-group's batched body over gathered inputs and
        return its validated per-write-flow stacked outputs (the shared
        core of both executor forms)."""
        outs = self._body(grp.tc, batch, grp)(*inputs)
        return list(self._normalize_outs(grp.tc, outs))

    # -- pure store-passing execution ------------------------------------
    @staticmethod
    def _apply_in_specs(grp: WaveGroup, inputs: List[Any]) -> List[Any]:
        """Apply the group's composed dep [type=...] specs to the
        gathered stacks (cast/transpose act on the last two axes, so
        batched application is exact; ReshapeSpec.fn must be batch-safe
        for compiled execution)."""
        if not any(s is not None for s in grp.in_specs):
            return inputs
        return [s.apply(x) if s is not None else x
                for s, x in zip(grp.in_specs, inputs)]

    def run_arrays(self, stores: Dict[str, Any]) -> Dict[str, Any]:
        """stores: name → (ntiles+1, mb, nb) array (last slot = dummy)."""
        jnp = self.jnp
        stores = dict(stores)
        for wave in self.plan.waves:
            # gather-before-scatter inside the wave: snapshot reads
            snapshot = stores
            updates: List[Tuple[str, Any, Any]] = []
            for grp in wave:
                B = len(grp.tasks)
                Bp = 1 << (B - 1).bit_length() if self.bucket else B
                inputs = []
                for (name, idx) in grp.in_slots:
                    gidx = self._pad(idx, Bp, 0)
                    inputs.append(snapshot[name][gidx])
                inputs = self._apply_in_specs(grp, inputs)
                outs = self._exec_group(grp, Bp, inputs)
                for (name, idx), val in zip(grp.out_slots, outs):
                    dummy = stores[name].shape[0] - 1
                    sidx = self._pad(idx, Bp, dummy)
                    updates.append((name, sidx, val))
            for name, sidx, val in updates:
                stores[name] = stores[name].at[sidx].set(
                    val.astype(stores[name].dtype))
        return stores

    # -- tile-dict execution ---------------------------------------------
    # The stacked-store form pays a full-store copy per wave for the
    # functional scatter (dominant on bandwidth-limited chips). In the
    # tile-dict form every tile is its own array: a wave stacks only the
    # tiles of its batch, and "scatter" is dict rebinding — zero copies
    # of untouched tiles. Preferred single-chip form; the stacked form
    # remains the input to the SPMD mesh path (sharded along slots).

    def make_tiles(self, host: bool = False
                   ) -> Dict[Tuple[str, int], Any]:
        """Tile dict from the collections. ``host=True`` keeps tiles as
        host numpy (for budgeted segmented execution: the HBM manager
        stages them in lazily instead of everything landing in device
        memory up front)."""
        import numpy as _np
        jnp = self.jnp
        tiles: Dict[Tuple[str, int], Any] = {}
        for name, dc in self.plan.collections.items():
            scratch = dc.scratch
            for key, slot in self.plan.slot_maps[name].items():
                if scratch:   # factor scratch: zeros, no host read
                    z = (_np.zeros if host else jnp.zeros)(
                        (dc.mb, dc.nb), dc.dtype)
                    tiles[(name, slot)] = z
                elif host:
                    tiles[(name, slot)] = _np.asarray(dc.data_of(key))
                else:
                    tiles[(name, slot)] = jnp.asarray(dc.data_of(key))
        return tiles

    def run_tile_dict(self, tiles: Dict[Tuple[str, int], Any]
                      ) -> Dict[Tuple[str, int], Any]:
        """Pure function tile-dict → tile-dict; jit for the fused form."""
        tiles = dict(tiles)
        for wave in self.plan.waves:
            snapshot = tiles           # values are immutable jax arrays
            updates: List[Tuple[Tuple[str, int], Any]] = []
            for grp in wave:
                B = len(grp.tasks)
                inputs = [self.jnp.stack([snapshot[(name, int(s))]
                                          for s in idx])
                          for (name, idx) in grp.in_slots]
                inputs = self._apply_in_specs(grp, inputs)
                outs = self._exec_group(grp, B, inputs)
                for (name, idx), val in zip(grp.out_slots, outs):
                    for b, s in enumerate(idx):
                        updates.append(((name, int(s)), val[b]))
            for k, v in updates:
                tiles[k] = v
        return tiles

    # -- segmented tile-dict execution -----------------------------------
    # Whole-DAG jit compiles every wave-group's ops into one XLA program:
    # compile time grows with task count (42 s at 120 tasks, minutes at
    # thousands). The segmented form dispatches one cached jitted segment
    # per (class, bucket) shape: compile cost scales with the number of
    # DISTINCT shapes (a handful per class — power-of-two bucketed), not
    # with tasks or waves, and segments are reused across waves, runs and
    # problem sizes with the same tile shape. JAX async dispatch keeps
    # the per-call overhead pipelined. Trade-off: the program can't be
    # fused across waves, so prefer run_tile_dict/jit for small DAGs and
    # the panel path for dense one-matrix DAGs.

    def _segment(self, grp: WaveGroup, batch: int) -> Callable:
        chore = self._chore(grp.tc)
        hooked = self._hook_applies(chore, grp)
        shapes = tuple(
            (self.plan.collections[name].mb,
             self.plan.collections[name].nb,
             np.dtype(self.plan.collections[name].dtype).str)
            for (name, _idx) in grp.in_slots) if grp.in_slots else ()
        sig = tuple(s.key if s is not None else None
                    for s in grp.in_specs)
        key = (grp.tc.name, batch, hooked, shapes, sig)
        fn = self._segments.get(key)
        if fn is None:
            body = self._body(grp.tc, batch,
                              grp if hooked else None)
            specs = tuple(grp.in_specs)

            def seg(*ins, _b=body, _tc=grp.tc, _specs=specs):
                if any(s is not None for s in _specs):
                    ins = [s.apply(x) if s is not None else x
                           for s, x in zip(_specs, ins)]
                return tuple(self._normalize_outs(_tc, _b(*ins)))

            # shared across executors (and processes, via the store)
            # when the class's bodies fingerprint stably: rebuilding an
            # executor for the same (class, bucket) never re-traces.
            # Spec fns enter through sig keys only, so require stable
            # fingerprints for them too; else stay per-instance.
            body_fp = self._body_fp(grp.tc)
            spec_ok = all(
                s is None or
                compile_cache.function_fingerprint(s.apply)[0]
                for s in specs)
            if body_fp is not None and spec_ok:
                import jax
                sds = tuple(jax.ShapeDtypeStruct((batch, mb, nb), dt)
                            for (mb, nb, dt) in shapes)
                fn = compile_cache.cached_jit(
                    seg, key=("wf_segment", body_fp, key),
                    example_args=sds if sds else None)
            else:
                fn = self.jax.jit(seg)
            self._segments[key] = fn
        return fn

    def _split_group(self, grp: WaveGroup,
                     manager: Optional[Any]) -> List[WaveGroup]:
        """Split a wave-group so one sub-batch's tile working set
        (inputs + outputs) fits in ~half the manager's budget."""
        if manager is None:
            return [grp]
        tile_bytes = max(
            dc.mb * dc.nb * np.dtype(dc.dtype).itemsize
            for dc in self.plan.collections.values())
        max_tiles = max(1, (manager.zone.capacity // 2) // tile_bytes)
        per_task = max(1, len(grp.in_slots) + len(grp.out_slots))
        chunk = max(1, max_tiles // per_task)
        if len(grp.tasks) <= chunk:
            return [grp]
        subs = []
        for lo in range(0, len(grp.tasks), chunk):
            hi = lo + chunk
            subs.append(WaveGroup(
                tc=grp.tc, level=grp.level, tasks=grp.tasks[lo:hi],
                in_slots=[(n, idx[lo:hi]) for (n, idx) in grp.in_slots],
                out_slots=[(n, idx[lo:hi])
                           for (n, idx) in grp.out_slots],
                in_specs=list(grp.in_specs)))
        return subs

    def _use_schedule(self) -> Dict[Tuple[str, int], List[int]]:
        """Wave indices at which each tile is read — the static schedule
        that makes Belady eviction possible for the HBM manager."""
        uses: Dict[Tuple[str, int], List[int]] = {}
        for w, wave in enumerate(self.plan.waves):
            for grp in wave:
                for (name, idx) in grp.in_slots:
                    for s in idx:
                        uses.setdefault((name, int(s)), []).append(w)
        return uses

    _NEVER = 1 << 30      # "never read again" — the ideal evictee

    def run_tile_dict_segmented(self, tiles: Dict[Tuple[str, int], Any],
                                manager: Optional[Any] = None
                                ) -> Dict[Tuple[str, int], Any]:
        """Tile-dict execution dispatched wave-by-wave through cached
        per-(class, bucket) jitted segments (bounded compile time).

        With an :class:`~..device.hbm.HBMManager`, tile residency is
        bounded by its budget: inputs are staged in (evicting the tile
        with the farthest next use — the plan gives Belady's policy for
        free), outputs registered, and the next wave's inputs are
        prefetched while the current wave's dispatches are in flight.
        Problems larger than the budget complete by spilling to host.
        """
        from ..utils import mca_param
        jnp = self.jnp
        tiles = dict(tiles)
        if manager is not None:
            uses = self._use_schedule()
            # spills rebind the tiles dict to the host copy, so the
            # executor drops its device reference and XLA can actually
            # free the buffer (logical AND physical residency agree)
            _spill = tiles.__setitem__
            for key, val in tiles.items():
                # register lazily (host-side): tiles stage in at first use
                manager.register(key, val, spill=_spill,
                                 next_use=uses.get(key, [self._NEVER])[0])

        def _next_use(key, w):
            for u in uses.get(key, ()):
                if u > w:
                    return u
            return self._NEVER

        prefetch = manager is not None and bool(
            mca_param.get("device.hbm_prefetch", 1))
        for w, wave in enumerate(self.plan.waves):
            snapshot = dict(tiles)     # gather-before-scatter snapshot
            updates: List[Tuple[Tuple[str, int], Any]] = []
            for grp in wave:
                # under a budget, split oversized groups so one
                # sub-batch's working set fits (the reference stages
                # per task; a k=0 trailing-update group can otherwise
                # reference nearly the whole matrix at once)
                for sub in self._split_group(grp, manager):
                    gkeys = [(name, int(s))
                             for (name, idx) in sub.in_slots
                             for s in idx]
                    if manager is not None:
                        protect = tuple(gkeys)
                        for key in gkeys:
                            snapshot[key] = manager.ensure(
                                key, snapshot.get(key), protect=protect,
                                next_use=_next_use(key, w))
                    B = len(sub.tasks)
                    Bp = 1 << (B - 1).bit_length() if self.bucket else B
                    inputs = []
                    for (name, idx) in sub.in_slots:
                        pidx = self._pad(idx, Bp, int(idx[0]))
                        inputs.append(jnp.stack(
                            [snapshot[(name, int(s))] for s in pidx]))
                    outs = self._segment(sub, Bp)(*inputs)
                    for (name, idx), val in zip(sub.out_slots, outs):
                        for b, s in enumerate(idx):  # padding dropped
                            updates.append(((name, int(s)), val[b]))
            for k, v in updates:
                tiles[k] = v
                if manager is not None:
                    manager.put(k, v, spill=_spill,
                                next_use=_next_use(k, w))
            if prefetch and w + 1 < len(self.plan.waves):
                # stage the next wave's inputs while this wave's async
                # dispatches drain (device_cuda stage-in stream analog).
                # Opportunistic only: best_effort staging fills FREE
                # space and never evicts — pinning or thrashing the
                # resident set would defeat budgets sized for one
                # sub-group
                for grp in self.plan.waves[w + 1]:
                    for (name, idx) in grp.in_slots:
                        for s in idx:
                            key = (name, int(s))
                            tiles[key] = manager.ensure(
                                key, tiles.get(key), best_effort=True,
                                next_use=_next_use(key, w))
        return tiles

    def write_back_tiles(self, tiles: Dict[Tuple[str, int], Any]) -> None:
        tspecs = self.plan.terminal_specs
        for name, dc in self.plan.collections.items():
            if dc.scratch:
                continue      # nobody reads factor scratch after the run
            for key, slot in self.plan.slot_maps[name].items():
                v = tiles[(name, slot)]
                spec = tspecs.get((name, slot))
                dc.write_tile(key, spec.apply(v) if spec is not None else v)

    # -- host-driven run --------------------------------------------------
    def make_stores(self) -> Dict[str, Any]:
        jnp = self.jnp
        stores = {}
        for name, dc in self.plan.collections.items():
            if dc.scratch:
                n = len(self.plan.slot_maps[name])
                stores[name] = jnp.zeros((n + 1, dc.mb, dc.nb), dc.dtype)
                continue
            arr, _ = dc.to_stacked()
            dummy = jnp.zeros((1,) + arr.shape[1:], dtype=arr.dtype)
            stores[name] = jnp.concatenate([arr, dummy], axis=0)
        return stores

    def write_back(self, stores: Dict[str, Any]) -> None:
        tspecs = self.plan.terminal_specs
        for name, dc in self.plan.collections.items():
            if dc.scratch:
                continue
            if any(k[0] == name for k in tspecs):
                # per-tile path: some slots carry terminal [type=...]
                # conversions the stacked write can't express
                for key, slot in self.plan.slot_maps[name].items():
                    v = stores[name][slot]
                    spec = tspecs.get((name, slot))
                    dc.write_tile(key, spec.apply(v)
                                  if spec is not None else v)
                continue
            dc.from_stacked(stores[name][:-1], self.plan.slot_maps[name])

    def run(self, jit: bool = True) -> float:
        t0 = time.perf_counter()
        stores = self.make_stores()
        fn = self.jitted if jit else self.run_arrays
        out = fn(stores)
        for v in out.values():
            v.block_until_ready()
        dt = time.perf_counter() - t0
        self.write_back(out)
        return dt
