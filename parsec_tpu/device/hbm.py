"""Bounded device-memory management for tile workloads.

Reference semantics: the CUDA device module reserves tiles against a
zone-malloc'd device heap, evicts cold copies through clean/dirty LRU
lists, and stages data in/out around kernel launches
(device_cuda_module.c:864-1179, device_gpu.h:115-136,
utils/zone_malloc.c). On TPU, XLA/PJRT owns physical HBM, so this layer
manages *logical residency*: which tiles live as device ``jax.Array``
and which are spilled to host numpy, with the
:class:`~..utils.zone_malloc.ZoneAllocator` as the byte-accounting
structure (same role as the reference's zone heap).

Two eviction policies:

- **plan-informed** (``next_use`` schedules): the compiled executors
  know every tile's future use waves from the
  :class:`~..compiled.wavefront.WavefrontPlan`, so eviction picks the
  resident tile whose next use is farthest away (Belady's optimal
  policy) — strictly better than LRU, and only possible because the
  dataflow plan is static. This is the TPU-first upgrade over the
  reference's runtime LRU.
- **LRU** (no schedule): the host-runtime path (TPUDevice) registers
  collection tiles as tasks write them; when over budget the
  least-recently-used tile is rewritten into its collection as host
  numpy, releasing the device buffer.

Spilling moves bytes across PCIe/the tunnel — correct but slow, exactly
like the reference's eviction under memory pressure. A POTRF sized
beyond the budget completes instead of aborting (tests exercise this
with an artificially small budget on CPU).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Hashable, Optional, Tuple

import numpy as np

from ..utils import mca_param
from ..utils.debug import debug_verbose
from ..utils.zone_malloc import ZoneAllocator

mca_param.register("device.hbm_budget_mb", 0,
                   help="device-memory budget for tile residency "
                        "management (0 = unlimited, no spilling)")
mca_param.register("device.hbm_prefetch", 1,
                   help="prefetch next-wave tiles during segmented "
                        "execution (async device_put overlap)")


def _nbytes(value: Any) -> int:
    nb = getattr(value, "nbytes", None)
    if nb is not None:
        return int(nb)
    return int(np.asarray(value).nbytes)


class HBMManager:
    """Residency manager over a logical device heap.

    Entries are keyed by any hashable (tile coordinates, collection
    keys). Each entry holds EITHER a device value (resident, accounted
    in the zone) or a host value (spilled). ``ensure`` stages entries
    in, evicting under pressure; ``put`` registers newly produced
    device values (evicting others to make the budget hold).
    """

    def __init__(self, budget_bytes: int, unit: int = 4096):
        import jax
        self.jax = jax
        self.budget = budget_bytes
        self.unit = unit
        # the budget is PER CHIP: one zone per jax device tiles land on
        # (per-chip device modules stage copies onto their own chips —
        # a single global zone would not bound any real HBM)
        self._zones: Dict[Any, ZoneAllocator] = {}
        self._entries: Dict[Hashable, Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._clock = 0
        self._stage_dev = None       # placement guess for reserve-first
        self.stats = {"stage_in": 0, "spills": 0, "bytes_staged": 0,
                      "bytes_spilled": 0, "peak_bytes": 0,
                      # eviction-policy split: victims chosen by the
                      # plan's next-use schedule (Belady) vs the LRU
                      # fallback (no schedule info on the victim)
                      "evict_belady": 0, "evict_lru": 0,
                      # owner-computes reads served by the remote
                      # stage-in path (fetch_tiles: segmented fetch
                      # straight into an HBM slot, no host copy kept)
                      "remote_stage_in": 0}

    # ---------------------------------------------------------- internal
    def _zone_for(self, dev) -> ZoneAllocator:
        z = self._zones.get(dev)
        if z is None:
            z = self._zones[dev] = ZoneAllocator(self.budget,
                                                 unit=self.unit)
        return z

    @property
    def zone(self) -> ZoneAllocator:
        """The default device's zone (per-chip budget view)."""
        with self._lock:
            return self._zone_for(self.jax.devices()[0])

    def _account_alloc(self, nbytes: int, dev) -> Optional[int]:
        zone = self._zone_for(dev)
        off = zone.malloc(nbytes)
        if off is not None:
            used = zone.bytes_used()
            if used > self.stats["peak_bytes"]:
                self.stats["peak_bytes"] = used   # max per-chip usage
        return off

    def _evict_one(self, protect: Tuple[Hashable, ...], dev) -> bool:
        """Spill the best victim ON ``dev`` not in ``protect``.
        Plan-informed when next_use hints exist (farthest next use
        first; never-used-again tiles are ideal victims), LRU
        otherwise."""
        with self._lock:
            best_key, best_rank = None, None
            for key, e in self._entries.items():
                if e["offset"] is None or key in protect or \
                        e.get("device") != dev or e.get("pins", 0) > 0:
                    continue
                nu = e.get("next_use")
                # rank: (next_use descending, last_use ascending);
                # next_use None = no schedule info -> pure LRU term
                rank = ((nu if nu is not None else -1), -e["last_use"])
                if best_rank is None or rank > best_rank:
                    best_key, best_rank = key, rank
            if best_key is None:
                return False
            e = self._entries[best_key]
            spill_cb = e.get("spill")
            host = np.asarray(e["value"])       # D2H (the slow path)
            if spill_cb is not None:
                spill_cb(best_key, host)
            e["value"] = host
            self._zone_for(dev).free(e["offset"])
            e["offset"] = None
            e["device"] = None
            self.stats["spills"] += 1
            self.stats["evict_belady" if e.get("next_use") is not None
                       else "evict_lru"] += 1
            self.stats["bytes_spilled"] += host.nbytes
            debug_verbose(3, "hbm", "spilled %r (%d bytes)", best_key,
                          host.nbytes)
            return True

    def _reserve(self, nbytes: int, protect: Tuple[Hashable, ...],
                 dev) -> int:
        off = self._account_alloc(nbytes, dev)
        while off is None:
            if not self._evict_one(protect, dev):
                zone = self._zone_for(dev)
                raise MemoryError(
                    f"HBM budget too small: cannot reserve {nbytes} "
                    f"bytes on {dev} (budget {zone.capacity}, in use "
                    f"{zone.bytes_used()}, all resident tiles pinned)")
            off = self._account_alloc(nbytes, dev)
        return off

    @staticmethod
    def _device_of(value) -> Any:
        return getattr(value, "device", None)

    # ------------------------------------------------------------ public
    def ensure(self, key: Hashable, value: Any = None,
               protect: Tuple[Hashable, ...] = (),
               next_use: Optional[int] = None,
               spill: Optional[Callable] = None,
               best_effort: bool = False) -> Any:
        """Return the device-resident value for ``key``, staging it in
        (and evicting under pressure) if needed. ``value`` supplies the
        data on first sight; ``protect`` keys are not eviction
        candidates during this call (the current wave's working set).
        ``best_effort=True`` never evicts: if no free space remains the
        current (possibly host) value is returned unstaged — the
        prefetch contract."""
        with self._lock:
            self._clock += 1
            e = self._entries.get(key)
            if e is None:
                if value is None:
                    raise KeyError(f"unknown HBM entry {key!r}")
                e = {"value": value, "offset": None, "last_use": 0,
                     "next_use": next_use, "spill": spill,
                     "device": None}
                self._entries[key] = e
            if spill is not None:
                e["spill"] = spill
            if next_use is not None:
                e["next_use"] = next_use
            e["last_use"] = self._clock
            if e["offset"] is None:
                nb = _nbytes(e["value"])
                host_val = e["value"]
                if isinstance(host_val, self.jax.Array):
                    # already in HBM: account it where it lives
                    dev = self._device_of(host_val)
                    if best_effort:
                        off = self._account_alloc(nb, dev)
                        if off is None:
                            return host_val
                    else:
                        off = self._reserve(nb, protect, dev)
                    e["offset"], e["device"] = off, dev
                    return host_val
                # host value: probe free space on the GUESSED landing
                # device first (no eviction!) so a failed best_effort
                # probe costs zero transfers; eviction decisions are
                # only ever made against the device the value actually
                # lands on. The one-tile window between staging and
                # reservation is the only transient physical overshoot.
                guess = self._stage_dev or self.jax.devices()[0]
                off = self._account_alloc(nb, guess)
                if off is None and best_effort:
                    return host_val            # no room: stay spilled
                try:
                    staged = self.jax.device_put(host_val)
                except Exception:
                    if off is not None:        # never leak the probe
                        self._zone_for(guess).free(off)
                    raise
                dev = self._device_of(staged)
                if dev != guess and off is not None:
                    self._zone_for(guess).free(off)
                    off = None
                if off is None:
                    off = self._account_alloc(nb, dev)
                if off is None:
                    if best_effort:
                        del staged             # actual chip full too
                        return host_val
                    off = self._reserve(nb, protect, dev)
                self._stage_dev = dev
                e["offset"], e["device"] = off, dev
                e["value"] = staged
                self.stats["stage_in"] += 1
                self.stats["bytes_staged"] += nb
            return e["value"]

    def put(self, key: Hashable, value: Any,
            protect: Tuple[Hashable, ...] = (),
            next_use: Optional[int] = None,
            spill: Optional[Callable] = None,
            pin: bool = False) -> None:
        """Register a device value just produced (already in HBM).

        ``pin=True`` marks the entry ineligible for eviction until
        :meth:`unpin` — callers that put a value and then publish it
        elsewhere (e.g. the runtime writing the tile into a collection
        after tracking it) close the window where an eviction's spill
        would race the publish (ADVICE round 2: the spill's host write
        could be overwritten by the device value, leaving the
        collection holding an unaccounted device array)."""
        with self._lock:
            self._clock += 1
            old = self._entries.get(key)
            if old is not None and old["offset"] is not None:
                self._zone_for(old.get("device")).free(old["offset"])
                old["offset"] = None    # _reserve may raise: never leave
                #                         a dangling offset to double-free
            nb = _nbytes(value)
            dev = self._device_of(value)
            try:
                off = self._reserve(nb, protect + (key,), dev)
            except MemoryError:
                # the value exceeds the whole budget: drop the entry
                # entirely — keeping the superseded old value would pin
                # a dead version and serve stale data
                self._entries.pop(key, None)
                raise
            self._entries[key] = {
                "value": value, "offset": off, "last_use": self._clock,
                # pins ACCUMULATE across re-puts: a second writer's
                # pinned put while the first is inside its track->write
                # window must not drop the first pin (native workers
                # complete concurrently)
                "pins": (old or {}).get("pins", 0) + (1 if pin else 0),
                "next_use": next_use, "device": dev,
                "spill": spill if spill is not None else
                (old or {}).get("spill")}

    def unpin(self, key: Hashable) -> None:
        """Release one :meth:`put` pin; no-op for unknown keys (the
        entry may have been dropped by a failed oversized put)."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.get("pins", 0) > 0:
                e["pins"] -= 1

    def register(self, key: Hashable, value: Any,
                 next_use: Optional[int] = None,
                 spill: Optional[Callable] = None) -> None:
        """Record an entry WITHOUT staging it: host values stay on host
        until first ``ensure`` (lazy stage-in); device values are
        accounted immediately (they already occupy HBM)."""
        with self._lock:
            if key in self._entries:
                return
            e = {"value": value, "offset": None, "last_use": 0,
                 "next_use": next_use, "spill": spill, "device": None}
            self._entries[key] = e
            if isinstance(value, self.jax.Array):
                dev = self._device_of(value)
                e["offset"] = self._reserve(_nbytes(value), (key,), dev)
                e["device"] = dev

    def fetch_tiles(self, dc, keys_owners, comm, scope: str = "",
                    next_use: Optional[int] = None,
                    protect: Tuple[Hashable, ...] = ()) -> list:
        """Owner-computes remote stage-in (ROADMAP item 1): resolve a
        batch of collection tiles into DEVICE residency, treating
        "remote chip" as a stage-in source. Local (or already-tracked)
        tiles stage from the collection; remote tiles issue ONE
        concurrent segmented fetch (``CommEngine.fetch_tiles(...,
        stage=True)`` — per-segment H2D on the comm thread) and are
        accounted straight into their HBM slots with the ``next_use``
        hint intact, instead of materializing a host copy first.

        Entries are keyed per ``scope`` (the gathering taskpool's name
        — the cross-rank registry identity), so a tile re-gathered
        across waves of one pool stays resident while a later pool can
        never read a stale cached version. The same dataflow-ordering
        contract as ``fetch_tile`` applies: the tile must be final on
        its owner when this is called (CTL-gather). Returns values in
        order."""
        import weakref
        pairs = list(keys_owners)
        my_rank = getattr(comm, "rank", 0)
        single = getattr(comm, "nb_ranks", 1) <= 1
        dc_ref = weakref.ref(dc)

        def _sweep_tag(_k, _host, dc_ref=dc_ref):
            # no write-back: a fetched INPUT tile spills by dropping to
            # host only. The dc weakref default is the context sweep's
            # liveness tag (_hbm_entry_dead) — entries die with their
            # collection.
            return None

        out: Dict[int, Any] = {}
        fetch_slots, fetch_pairs = [], []
        for i, (key, owner) in enumerate(pairs):
            k = tuple(key) if isinstance(key, (tuple, list)) else (key,)
            mkey = ("fetch", scope, id(dc), k)
            with self._lock:
                have = mkey in self._entries
            if have:
                out[i] = self.ensure(mkey, protect=protect,
                                     next_use=next_use)
            elif owner == my_rank or single:
                out[i] = self.ensure(mkey, value=dc.data_of(key),
                                     protect=protect, next_use=next_use,
                                     spill=_sweep_tag)
            else:
                fetch_slots.append((i, mkey))
                fetch_pairs.append((key, owner))
        if fetch_pairs:
            vals = comm.fetch_tiles(dc, fetch_pairs, scope=scope,
                                    stage=True)
            for (i, mkey), v in zip(fetch_slots, vals):
                out[i] = self.ensure(mkey, value=v, protect=protect,
                                     next_use=next_use, spill=_sweep_tag)
                with self._lock:
                    self.stats["remote_stage_in"] += 1
        return [out[i] for i in range(len(pairs))]

    def hint(self, key: Hashable, next_use: Optional[int] = None) -> None:
        """Refresh an entry's next-use hint + LRU stamp WITHOUT staging
        or evicting — the KV state layer's page-touch path (every page
        write/read advances its expected next use, so page-level Belady
        ranks cold prefix pages as victims ahead of hot ones). No-op
        for unknown keys."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return
            self._clock += 1
            e["last_use"] = self._clock
            if next_use is not None:
                e["next_use"] = next_use

    def value(self, key: Hashable) -> Any:
        """Current value (device or spilled host) without staging."""
        with self._lock:
            return self._entries[key]["value"]

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(z.bytes_used() for z in self._zones.values())

    def drop(self, key: Hashable) -> None:
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None and e["offset"] is not None:
                self._zone_for(e.get("device")).free(e["offset"])

    def sweep(self, dead: Callable[[Hashable, Dict[str, Any]], bool]
              ) -> int:
        """Drop every entry for which ``dead(key, entry)`` is true —
        e.g. tiles of garbage-collected collections. Returns the count
        dropped."""
        with self._lock:
            victims = [k for k, e in self._entries.items() if dead(k, e)]
            for k in victims:
                self.drop(k)
            return len(victims)


def track_collection_write(mgr: Optional[HBMManager], dc, key,
                           value) -> Optional[Hashable]:
    """Track a device-resident tile a task is about to write into its
    collection (pinned — see :meth:`HBMManager.put`); returns the
    manager key to :meth:`~HBMManager.unpin` AFTER the collection write,
    or None when the value is untracked (host value / over-budget).

    Shared by the host runtime (core.context complete_task) and the
    native executor so both completion paths enforce the budget the
    same way. The spill closure holds the collection weakly — dead
    collections' entries are swept when their taskpool terminates
    instead of being pinned forever."""
    import weakref
    if mgr is None or not isinstance(value, mgr.jax.Array):
        return None
    k = tuple(key) if isinstance(key, (tuple, list)) else (key,)
    dc_ref = weakref.ref(dc)

    def _spill(_k, host, dc_ref=dc_ref, key=key):
        target = dc_ref()
        if target is not None:
            target.write_tile(key, host)

    mkey = (id(dc), k)
    try:
        mgr.put(mkey, value, spill=_spill, pin=True)
    except MemoryError:
        from ..utils.debug import warning
        warning("hbm", "tile %r exceeds the device budget alone; "
                "left untracked", key)
        return None
    return mkey


def manager_from_mca() -> Optional[HBMManager]:
    """Build an :class:`HBMManager` from the MCA budget param, or None
    when unlimited."""
    mb = int(mca_param.get("device.hbm_budget_mb", 0))
    if mb <= 0:
        return None
    return HBMManager(mb * (1 << 20))
