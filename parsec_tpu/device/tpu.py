"""TPU device module.

Replaces the reference's CUDA device pipeline
(mca/device/cuda/device_cuda_module.c, 2,765 LoC) with an XLA-native
design. The reference pipelines each GPU task through stage-in → kernel →
stage-out streams with event-driven progress; on TPU the equivalent roles
are played by XLA/PJRT itself:

- *stage-in/out*: ``jax.device_put`` / implicit transfer of host values;
  tile data produced by previous TPU tasks stays resident in HBM as
  ``jax.Array`` and flows to successors without host bounce.
- *streams + events*: JAX dispatch is asynchronous — calling a jitted body
  returns immediately with future-backed arrays, so consecutive tasks
  pipeline on device; blocking only happens at final writebacks.
- *kernel lookup* (reference cuda_find_incarnation, dyld by name): bodies
  are Python jnp/pallas functions jitted per task class on first use and
  cached (XLA compile cache handles shape variants).

The *batched* execution path — many ready tasks of one class fused into a
single vmapped XLA call so the MXU sees one large batched matmul instead of
many small launches — lives in ``parsec_tpu.compiled`` and is the
performance path for dense tiled algorithms.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict

from .base import Device
from ..core.task import Chore, DeviceType, HookReturn, Task
from ..utils.debug import debug_verbose


class TPUDevice(Device):
    device_type = DeviceType.TPU
    name = "tpu"

    def __init__(self, jax_device: Any = None) -> None:
        """One module instance per chip (reference: one
        parsec_device_cuda_module_t per GPU, device_cuda_module.c:326).
        ``jax_device`` pins this module to a specific ``jax.Device``;
        default = the first visible device."""
        super().__init__()
        import jax
        self.jax = jax
        self.jax_device = jax_device if jax_device is not None \
            else jax.devices()[0]
        self.platform = self.jax_device.platform
        # load-balancing weight: accelerators drastically out-throughput the
        # inline-CPU device (reference GFLOPS table device_cuda_module.c:53)
        self.weight = 100.0 if self.platform != "cpu" else 2.0
        self.name = f"tpu{self.jax_device.id}"
        self._jit_cache: Dict[Any, Callable] = {}
        self._cache_lock = threading.Lock()
        debug_verbose(3, "device", "TPU device on %s (%s)",
                      self.jax_device, self.platform)

    def _jitted(self, task: Task, chore: Chore) -> Callable:
        key = (task.task_class.tc_id, task.taskpool.taskpool_id, id(chore))
        fn = self._jit_cache.get(key)
        if fn is None:
            with self._cache_lock:
                fn = self._jit_cache.get(key)
                if fn is None:
                    body = chore.hook
                    # bodies take (task, *tiles); the task argument is
                    # host-side metadata — close over it as static
                    jit_body = self.jax.jit(
                        lambda *tiles, _b=body: _b(None, *tiles))
                    fn = jit_body
                    self._jit_cache[key] = fn
        return fn

    def execute(self, es, task: Task, chore: Chore) -> HookReturn:
        # Bodies that need task metadata (locals) opt out of the jit cache
        # by setting chore.batchable = False → called directly (they may
        # jit internally with locals as static args).
        if not chore.batchable:
            return self._run_hook(task, chore)
        jitted = self._jitted(task, chore)

        def hook(t, *tiles):
            # pin this module's chip: default_device alone does NOT
            # decide placement — committed inputs win (and inputs
            # committed to different chips make jit raise), so stage
            # every input onto this module's device explicitly
            # (device_put is a no-op for already-resident buffers)
            staged = [self.jax.device_put(x, self.jax_device)
                      if x is not None else None for x in tiles]
            with self.jax.default_device(self.jax_device):
                return jitted(*staged)

        wrapped = Chore(device_type=chore.device_type, hook=hook,
                        evaluate=chore.evaluate)
        return self._run_hook(task, wrapped)
