"""TPU device module.

Replaces the reference's CUDA device pipeline
(mca/device/cuda/device_cuda_module.c, 2,765 LoC) with an XLA-native
design. The reference pipelines each GPU task through stage-in → kernel →
stage-out streams with event-driven progress; on TPU the equivalent roles
are played by XLA/PJRT itself:

- *stage-in/out*: ``jax.device_put`` / implicit transfer of host values;
  tile data produced by previous TPU tasks stays resident in HBM as
  ``jax.Array`` and flows to successors without host bounce.
- *streams + events*: JAX dispatch is asynchronous — calling a jitted body
  returns immediately with future-backed arrays, so consecutive tasks
  pipeline on device; blocking only happens at final writebacks.
- *kernel lookup* (reference cuda_find_incarnation, dyld by name): bodies
  are Python jnp/pallas functions jitted per task class on first use and
  cached (XLA compile cache handles shape variants).

The *batched* execution path — many ready tasks of one class fused into a
single vmapped XLA call so the MXU sees one large batched matmul instead of
many small launches — lives in ``parsec_tpu.compiled`` and is the
performance path for dense tiled algorithms.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, List, Tuple

from .base import Device
from ..core.task import Chore, DeviceType, HookReturn, Task
from ..utils import mca_param
from ..utils.debug import debug_verbose, warning

# Measured trade-off (v5e through the axon remote tunnel, host-runtime
# POTRF n=4096/nb=512, one-shot taskpools): batch dispatch completes in
# ~3-4 s vs ~0.9-1.6 s for per-task sync dispatch — every batch shape
# pays a trace + remote-compile-cache round trip (~50 ms) that a
# ONE-SHOT taskpool never amortizes, even with power-of-two bucketing,
# in-jit stacking and batch_hook reformulations (vmapped triangular ops
# alone measured ~90 ms/batch). On a LOCAL accelerator, where tracing
# is ~ms and there is no remote lookup, batching is the winning shape —
# hence the knob rather than a removal. Default: sync dispatch.
mca_param.register(
    "device.tpu.batch_dispatch", 0,
    help="per-device manager thread batching same-class ready tasks "
         "into one vmapped/batch_hook dispatch (the reference's "
         "progress_stream pipeline, device_cuda_module.c:1961-2097); "
         "0 = dispatch tasks synchronously from the worker threads "
         "(faster through remote-tunnel backends — see module note). "
         "Assumes single-incarnation task classes: a chore returning "
         "NEXT cannot fall through to a later incarnation here")


class TPUDevice(Device):
    device_type = DeviceType.TPU
    name = "tpu"

    def __init__(self, jax_device: Any = None) -> None:
        """One module instance per chip (reference: one
        parsec_device_cuda_module_t per GPU, device_cuda_module.c:326).
        ``jax_device`` pins this module to a specific ``jax.Device``;
        default = the first visible device."""
        super().__init__()
        import jax
        self.jax = jax
        self.jax_device = jax_device if jax_device is not None \
            else jax.devices()[0]
        self.platform = self.jax_device.platform
        # load-balancing weight: accelerators drastically out-throughput the
        # inline-CPU device (reference GFLOPS table device_cuda_module.c:53)
        self.weight = 100.0 if self.platform != "cpu" else 2.0
        self.name = f"tpu{self.jax_device.id}"
        if self.platform != "cpu":
            # comm staging target: the pipelined receive path (per-
            # segment device_put) and the HBM remote stage-in land
            # bytes straight on this module's chip instead of bouncing
            # through jax's default device (first accelerator module
            # wins; CPU meshes keep uncommitted default placement —
            # committing test arrays to one virtual device would make
            # mixed-placement jits raise)
            from ..comm import device_plane
            device_plane.set_stage_target(self.jax_device)
        self._jit_cache: Dict[Any, Callable] = {}
        self._cache_lock = threading.Lock()
        # batching manager (progress_stream analog): workers enqueue
        # ready tasks; one thread per device drains the queue, groups
        # same-class tasks and dispatches each group as ONE vmapped call
        self._pending: deque = deque()
        self._mgr_cv = threading.Condition()
        self._mgr_thread: threading.Thread | None = None
        self._mgr_stop = False
        self._vmap_cache: Dict[Any, Callable] = {}
        self.stats["batches"] = 0
        self.stats["batched_tasks"] = 0
        debug_verbose(3, "device", "TPU device on %s (%s)",
                      self.jax_device, self.platform)

    def _jitted(self, task: Task, chore: Chore) -> Callable:
        # per-device first-level lookup stays ONE dict hit (this runs
        # per task on the dispatch hot path — the PR 3 overhead budget);
        # the (tc_id, taskpool_id, id(chore)) key guards id() reuse of
        # a GC'd pool's chore. Jit-cache unification happens at BUILD
        # time only: on a miss, bodies with a stable code fingerprint
        # fetch their wrapper from the process-wide compile_cache store,
        # so a new taskpool, a new Context, or a second TPUDevice for
        # the same body never re-traces. Unstable fingerprints stay
        # per-instance — never shared on an id()-grade identity.
        key = (task.task_class.tc_id, task.taskpool.taskpool_id, id(chore))
        fn = self._jit_cache.get(key)
        if fn is None:
            with self._cache_lock:
                fn = self._jit_cache.get(key)
                if fn is None:
                    from ..utils import compile_cache
                    body = chore.hook
                    stable, fp = compile_cache.function_fingerprint(body)
                    if stable:
                        fn = compile_cache.cached_jit(
                            lambda *tiles, _b=body: _b(None, *tiles),
                            key=("tpu_body", fp), persist=False)
                    else:
                        # bodies take (task, *tiles); the task argument
                        # is host-side metadata — closed over as static
                        fn = self.jax.jit(
                            lambda *tiles, _b=body: _b(None, *tiles))
                    self._jit_cache[key] = fn
        return fn

    def execute(self, es, task: Task, chore: Chore) -> HookReturn:
        # Bodies that need task metadata (locals) opt out of the jit cache
        # by setting chore.batchable = False → called directly (they may
        # jit internally with locals as static args).
        # cached_get: execute() is per-task — a full registry get here
        # costs a lock + env resolve on the dispatch hot path
        if (chore.batchable or chore.batch_body is not None) and \
                int(mca_param.cached_get("device.tpu.batch_dispatch", 0)):
            # manager path (progress_stream analog): enqueue and return
            # ASYNC — the manager thread batches same-class ready tasks
            # into one vmapped dispatch and completes them; this device
            # keeps its in-flight load unit until then. Non-batchable
            # hooks participate when they provide batch_sig/batch_body
            # (DTD pure woven bodies).
            self._ensure_manager()
            enqueued = False
            with self._mgr_cv:
                # after shutdown() initiated a stop, the manager may
                # exit without ever seeing this task — fall through to
                # a synchronous run instead of hanging it in _pending
                if not self._mgr_stop:
                    self._pending.append((task, chore))
                    self._mgr_cv.notify()
                    enqueued = True
            if enqueued:
                return HookReturn.ASYNC
        if not chore.batchable:
            return self._run_hook(task, chore)
        return self._run_sync(task, chore)

    def _run_sync(self, task: Task, chore: Chore) -> HookReturn:
        jitted = self._jitted(task, chore)

        def hook(t, *tiles):
            # pin this module's chip: default_device alone does NOT
            # decide placement — committed inputs win (and inputs
            # committed to different chips make jit raise), so stage
            # every input onto this module's device explicitly
            # (device_put is a no-op for already-resident buffers)
            staged = [self.jax.device_put(x, self.jax_device)
                      if x is not None else None for x in tiles]
            with self.jax.default_device(self.jax_device):
                return jitted(*staged)

        wrapped = Chore(device_type=chore.device_type, hook=hook,
                        evaluate=chore.evaluate)
        return self._run_hook(task, wrapped)

    # ------------------------------------------------ batching manager
    # The reference pipelines each GPU task through a manager owning the
    # device's streams (progress_stream, device_cuda_module.c:1961-2097,
    # pending queue pushes at :2573-2589). Here the manager's leverage
    # is BATCHING: N same-class ready tasks become one vmapped XLA
    # dispatch, dividing the per-dispatch launch/link overhead by N
    # (the dominant cost of host-runtime execution on remote backends).

    def _ensure_manager(self) -> None:
        if self._mgr_thread is None:
            with self._cache_lock:
                if self._mgr_thread is None:
                    self._mgr_stop = False
                    t = threading.Thread(target=self._mgr_main,
                                         name=f"parsec-{self.name}-mgr",
                                         daemon=True)
                    self._mgr_thread = t
                    t.start()

    def shutdown(self) -> None:
        """Stop the batching manager (Context.fini): signal, wake,
        join — a leaked manager would spin its condition-wait forever
        and could complete tasks against a finalized context. Any tasks
        still queued (fini on an abort path with work in flight) are
        drained and their taskpools aborted so ASYNC waiters are
        released instead of hanging on a completion that will never
        come."""
        t = self._mgr_thread
        if t is None:
            return
        with self._mgr_cv:
            self._mgr_stop = True
            self._mgr_cv.notify()
        t.join(timeout=5.0)
        if t.is_alive():
            # stuck mid-batch (e.g. a minutes-long remote compile):
            # keep the thread reference so a later execute() cannot
            # spawn a SECOND manager racing this one on _pending; the
            # manager's own stopping branch drains-and-aborts _pending
            # whenever it finally exits
            warning("device", "%s manager did not stop within 5 s; "
                    "leaving it flagged to stop", self.name)
            return
        self._mgr_thread = None
        # safety net for ABNORMAL manager exit (an exception in the
        # grouping loop kills the thread without reaching its stopping-
        # branch drain): anything still queued has no completer — abort
        # so ASYNC waiters release instead of hanging
        with self._mgr_cv:
            leftover = list(self._pending)
            self._pending.clear()
        if leftover:
            warning("device", "%s manager left %d queued task(s) "
                    "(abnormal exit); aborting their taskpools",
                    self.name, len(leftover))
            err = RuntimeError(
                f"{self.name}: batching manager exited with the task "
                "still queued")
            for (task, _chore) in leftover:
                self.release_load()
                task.taskpool.abort(err)

    def _context(self):
        reg = self.registry
        return reg.context if reg is not None else None

    def _sig(self, values):
        """Batch-compatibility signature of one task's input values:
        tasks vmap together only when every position agrees on
        (None-ness, pytree structure, leaf shapes/dtypes). Values whose
        leaves aren't stackable arrays/scalars return None — the task
        runs as a singleton."""
        import numbers
        tu = self.jax.tree_util
        sig = []
        for v in values:
            if v is None:
                sig.append(None)
                continue
            leaves, treedef = tu.tree_flatten(v)
            leaf_sig = []
            for leaf in leaves:
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                    leaf_sig.append((tuple(leaf.shape),
                                     str(leaf.dtype)))
                elif isinstance(leaf, numbers.Number):
                    leaf_sig.append(("scalar", type(leaf).__name__))
                else:
                    return None          # unstackable: singleton
            sig.append((str(treedef), tuple(leaf_sig)))
        return tuple(sig)

    def _hook_ok(self, tc, chore: Chore,
                 group: List[Tuple[Task, Chore]]) -> bool:
        """May this group use the chore's hand-batched ``batch_hook``?
        Shared flows must hold ONE value object across the group (the
        wavefront executor's _hook_applies check, by value identity —
        a host-runtime TRSM wave shares its factor from one producer)."""
        if chore.batch_hook is None:
            return False
        shared = getattr(chore, "batch_hook_shared", None) or ()
        if not shared:
            return True
        for name in shared:
            first = group[0][0].data.get(name)
            if any(t.data.get(name) is not first for (t, _c) in group[1:]):
                return False
        return True

    def _vmapped(self, tp_id, tc, chore: Chore, sig: Tuple, Bp: int,
                 treedefs, use_hook: bool, bsig=None,
                 body_override: Callable = None) -> Callable:
        """Jitted batched dispatcher taking the batch as FLAT per-leaf
        arguments and stacking INSIDE the program — eager jnp.stack
        calls per batch are themselves slow dispatches on remote
        backends (measured: they erased the whole batching win).

        ``use_hook``: dispatch through the chore's hand-batched
        ``batch_hook`` (stacked READ flows, the wavefront executor's
        convention) instead of vmap — vmapped cholesky/triangular
        solves lower poorly on TPU (measured ~90 ms/batch where the
        wide-solve reformulation is ~1 ms)."""
        # per-device first-level lookup stays one dict hit per batch;
        # taskpool_id guards id(chore) reuse after GC (a recycled id
        # would silently serve the old pool's jitted body); bsig
        # distinguishes woven-body variants of one batch_body chore
        # (different value payloads/precision). Jit-cache unification
        # happens at build time: on a miss, when every involved body
        # fingerprints stably, the batched dispatcher comes from the
        # process-wide compile_cache store keyed by code fingerprints
        # (+ bsig/sig/bucket) — equal bodies across taskpools,
        # contexts, and device modules trace once.
        key = (tp_id, tc.tc_id, id(chore), bsig, sig, Bp, use_hook)
        fn = self._vmap_cache.get(key)
        if fn is None:
            from ..utils import compile_cache
            shared_key = None
            parts = []
            for f in ((chore.batch_hook if use_hook else None),
                      (body_override if body_override is not None
                       else None if use_hook else chore.hook),
                      chore.batch_body):
                if f is None:
                    parts.append("none")
                    continue
                ok, fp = compile_cache.function_fingerprint(f)
                if not ok:
                    parts = None
                    break
                parts.append(fp)
            if parts is not None:
                shared_key = ("tpu_vmap", tuple(parts),
                              repr(getattr(chore, "batch_hook_shared",
                                           None) or ()), bsig, sig, Bp,
                              use_hook, tc.name)
            body = chore.batch_hook if use_hook else \
                (body_override or chore.hook)
            mask = tuple(s is not None for s in sig)
            # READ-flow mask in non-CTL declaration order (batch_hook
            # receives only gathered READ flows, stacked)
            from ..core.task import FlowAccess
            read_mask = tuple(
                bool(f.access & FlowAccess.READ)
                for f in tc.flows if not f.is_ctl)
            # (treedef, n_leaves) per non-None position, in order
            pos_info = [(td, td.num_leaves) for td in treedefs]

            _is_override = body_override is not None

            def batched(*flat, _b=body, _mask=mask, _info=pos_info,
                        _Bp=Bp, _rm=read_mask, _hook=use_hook,
                        _ovr=_is_override):
                tu = self.jax.tree_util
                jnp = self.jax.numpy
                it = iter(flat)
                stacked = []
                for (td, nl) in _info:
                    cols = [[] for _ in range(nl)]
                    for _b_i in range(_Bp):
                        for li in range(nl):
                            cols[li].append(next(it))
                    stacked.append(tu.tree_unflatten(
                        td, [jnp.stack(c) for c in cols]))
                if _hook:
                    it3 = iter(stacked)
                    reads = []
                    for m, r in zip(_mask, _rm):
                        if not m:
                            continue
                        v = next(it3)    # consume EVERY stacked slot
                        if r:
                            reads.append(v)
                    return _b(*reads)

                def one(*vals):
                    if _ovr:
                        # pure woven body: positional flow values only
                        # (no task arg, no None placeholders — the
                        # grouping refuses None-valued flows)
                        return _b(*vals)
                    it2 = iter(vals)
                    args = [next(it2) if m else None for m in _mask]
                    return _b(None, *args)

                return self.jax.vmap(one)(*stacked)

            if shared_key is not None:
                fn = compile_cache.cached_jit(batched, key=shared_key,
                                              persist=False)
            else:
                fn = self.jax.jit(batched)
            with self._cache_lock:
                self._vmap_cache[key] = fn
        return fn

    def _complete_batch(self, entries) -> None:
        """Dispatch one same-signature group as a single vmapped call
        and complete every task (ASYNC contract: release_load + context
        complete_task per task). ``entries``: (task, chore, values,
        sig, bsig) tuples — values/sig computed once at grouping
        time."""
        ctx = self._context()
        group = [(t, c) for (t, c, _v, _s, _b) in entries]
        (t0_, chore) = group[0]
        tc = t0_.task_class
        per_task = [v for (_t, _c, v, _s, _b) in entries]
        try:
            if len(group) == 1:
                # batch_body chores self-jit in their hook — _run_sync's
                # jit wrapper would double-jit them
                hr = self._run_sync(t0_, chore) if chore.batchable \
                    else self._run_hook(t0_, chore)
                # the manager cannot fall through to a later chore the
                # way Context._execute_task does (batch_dispatch assumes
                # single-incarnation task classes — see the knob help):
                # surface a non-DONE return instead of silently
                # completing with stale/no outputs
                if hr != HookReturn.DONE:
                    raise RuntimeError(
                        f"{tc.name}: singleton dispatch returned "
                        f"{hr!r}; batch_dispatch supports only "
                        "single-incarnation (DONE) task classes")
            else:
                tu = self.jax.tree_util
                sig = entries[0][3]
                # power-of-two bucketing (the wavefront executor's
                # padding trick): arbitrary batch sizes would each
                # compile a fresh program — through a remote-compile
                # tunnel that costs seconds per NEW size; padding by
                # repeating the last task bounds the shape set to
                # {2, 4, 8, ...} per class
                B = len(group)
                Bp = 1 << (B - 1).bit_length()
                padded = per_task + [per_task[-1]] * (Bp - B)
                treedefs = []
                flat: List[Any] = []
                for pos, s in enumerate(sig):
                    if s is None:
                        continue
                    treedefs.append(
                        tu.tree_flatten(per_task[0][pos])[1])
                    for vals in padded:
                        for leaf in tu.tree_leaves(vals[pos]):
                            # re-commit only cross-device leaves: jit
                            # raises on mixed committed placements
                            if isinstance(leaf, self.jax.Array) and \
                                    getattr(leaf, "device", None) not in \
                                    (None, self.jax_device):
                                leaf = self.jax.device_put(
                                    leaf, self.jax_device)
                            flat.append(leaf)
                use_hook = self._hook_ok(tc, chore, group)
                bsig = entries[0][4]
                body_override = chore.batch_body(t0_) \
                    if (chore.batch_body is not None and not use_hook) \
                    else None
                with self.jax.default_device(self.jax_device):
                    res = self._vmapped(
                        t0_.taskpool.taskpool_id, tc, chore, sig, Bp,
                        treedefs, use_hook, bsig=bsig,
                        body_override=body_override)(*flat)
                outs_by_task = [
                    self._normalize(tc, self.jax.tree_util.tree_map(
                        lambda x, b=b: x[b], res))
                    for b in range(len(group))]
                for (t, _c), outs in zip(group, outs_by_task):
                    t.output.update(outs)
                with self._lock:
                    self.stats["tasks"] += len(group)
                self.stats["batches"] += 1
                self.stats["batched_tasks"] += len(group)
        except Exception as exc:  # noqa: BLE001 — abort, don't hang
            warning("device", "%s batch of %s failed: %s", self.name,
                    tc.name, exc)
            import traceback
            traceback.print_exc()
            for (t, _c) in group:
                self.release_load()
                t.taskpool.abort(exc)
            return
        for (t, _c) in group:
            self.release_load()
            try:
                ctx.complete_task(None, t)
            except Exception as exc:  # noqa: BLE001 — manager survives
                warning("device", "%s completion of %r failed: %s",
                        self.name, t, exc)
                import traceback
                traceback.print_exc()
                from ..utils import debug_history
                debug_history.dump_on_fatal(f"{self.name} completion")
                t.taskpool.abort(exc)

    def _normalize(self, tc, result) -> Dict[str, Any]:
        """Body result → dict keyed by output-flow name, with the same
        arity validation as Device._run_hook — a body bug must not be
        masked in batched mode."""
        out_flows = tc.output_flows
        if isinstance(result, dict):
            return result
        if isinstance(result, (tuple, list)):
            if len(result) != len(out_flows):
                raise ValueError(
                    f"{tc.name}: body returned {len(result)} values "
                    f"for {len(out_flows)} output flows")
            return {f.name: v for f, v in zip(out_flows, result)}
        if len(out_flows) != 1:
            raise ValueError(
                f"{tc.name}: single return value but {len(out_flows)} "
                f"output flows")
        return {out_flows[0].name: result}

    def _mgr_main(self) -> None:
        while True:
            with self._mgr_cv:
                while not self._pending and not self._mgr_stop:
                    self._mgr_cv.wait(timeout=0.5)
                stopping = self._mgr_stop
                drained = list(self._pending)
                self._pending.clear()
            if stopping:
                # a manager that missed shutdown()'s join window exits
                # HERE after its in-flight batch: abort whatever queued
                # meanwhile (execute() stops enqueueing once _mgr_stop
                # is set, but tasks may have landed before that) —
                # otherwise they sit in _pending as ASYNC forever with
                # no completer
                if drained:
                    warning("device", "%s manager exiting with %d "
                            "queued task(s); aborting their taskpools",
                            self.name, len(drained))
                    err = RuntimeError(
                        f"{self.name}: batching manager stopped with "
                        "the task still queued")
                    for (task, _chore) in drained:
                        self.release_load()
                        task.taskpool.abort(err)
                return
            # group by (taskpool, class, chore, input signature);
            # values/sig computed ONCE here and carried through
            groups: Dict[Tuple, List] = {}
            order: List[Tuple] = []
            for (task, chore) in drained:
                values = task.input_values()
                sig = self._sig(values)
                # batch_body chores additionally group by batch_sig
                # (equal keys ⇒ identical woven bodies) and cannot
                # batch None-valued flows (the woven call passes flow
                # values positionally, no None placeholders)
                bsig = None
                if chore.batch_sig is not None:
                    bsig = chore.batch_sig(task)
                    if sig is not None and any(s is None for s in sig):
                        sig = None
                key = (task.taskpool.taskpool_id,
                       task.task_class.tc_id, id(chore), bsig,
                       sig if sig is not None else ("solo", id(task)))
                if key not in groups:
                    groups[key] = []
                    order.append(key)
                groups[key].append((task, chore, values, sig, bsig))
            for key in order:
                self._complete_batch(groups[key])
