"""Device base class and registry (reference parsec/mca/device/device.c)."""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..core.task import Chore, DeviceType, HookReturn, Task
from ..utils import mca_param
from ..utils.debug import debug_verbose

mca_param.register("device.tpu.enabled", True, help="register the TPU device")
mca_param.register("device.tpu.max_devices", 0,
                   help="cap on per-chip TPU modules (0 = all visible)")


class Device:
    """A device module (parsec_device_module_t analog)."""

    device_type = DeviceType.NONE
    name = "device"

    def __init__(self) -> None:
        self.index = -1
        self.registry: Optional["Registry"] = None
        # statistics (reference device.h:132-141 per-device counters)
        self.stats = {"tasks": 0, "exec_s": 0.0,
                      "bytes_in": 0, "bytes_out": 0}
        # relative throughput weight for load balancing
        # (reference: GFLOPS weights, device_cuda_module.c:53-117)
        self.weight = 1.0
        self.load = 0.0
        self._lock = threading.Lock()
        # extensible per-device info slots (parsec_per_device_infos)
        from ..utils.info import InfoArray, per_device_infos
        self.infos = InfoArray(per_device_infos, self)

    def attach(self, registry: "Registry", index: int) -> None:
        self.registry = registry
        self.index = index

    def execute(self, es, task: Task, chore: Chore) -> HookReturn:
        raise NotImplementedError

    def shutdown(self) -> None:
        """Stop any device-owned threads (called from Context.fini);
        base devices have none."""

    def release_load(self) -> None:
        """Release the in-flight work unit ``Registry.device_for`` added.
        The context releases it automatically when ``execute`` returns
        anything but ASYNC; async devices own the unit until their
        manager completes the task and MUST call this then."""
        with self._lock:
            self.load = max(0.0, self.load - 1.0)

    def _run_hook(self, task: Task, chore: Chore) -> HookReturn:
        """Run the functional body and normalize outputs into
        ``task.output`` keyed by output-flow name."""
        from ..core.task import normalize_outputs
        t0 = time.perf_counter()
        inputs = task.input_values()
        result = chore.hook(task, *inputs)
        # the task object itself as the label: it is only ever
        # formatted inside the error branches (no per-task repr cost)
        outs = normalize_outputs(
            result, [f.name for f in task.task_class.output_flows],
            task)
        task.output.update(outs)
        with self._lock:
            self.stats["tasks"] += 1
            self.stats["exec_s"] += time.perf_counter() - t0
        return HookReturn.DONE

    def dump_statistics(self) -> Dict:
        return dict(self.stats, name=self.name, index=self.index)


class Registry:
    """Device registry (parsec_mca_device_* analog)."""

    def __init__(self, context) -> None:
        from .cpu import CPUDevice
        from .recursive import RecursiveDevice
        self.context = context
        self.devices: List[Device] = []
        self.add(CPUDevice())
        self.add(RecursiveDevice())
        if mca_param.get("device.tpu.enabled", True):
            try:
                # one module per visible chip (reference: per-GPU module
                # instances, device_cuda_module.c:326) so device_for can
                # load-balance across them by load x weight
                import jax
                from .tpu import TPUDevice
                limit = int(mca_param.get("device.tpu.max_devices", 0))
                devs = jax.devices()
                if limit > 0:
                    devs = devs[:limit]
                added = [self.add(TPUDevice(jd)) for jd in devs]
                if any(d.platform != "cpu" for d in added):
                    # a REAL accelerator is registered: the CPU device's
                    # eager jnp ops would dispatch op-by-op to the same
                    # chip (~0.3 s/task through a remote tunnel) — make
                    # it a last resort, not a load-balancing peer
                    # (reference: the GFLOPS weight table keeps CPU
                    # cores ~100x below GPUs, device_cuda_module.c:53)
                    self.devices[0].weight = 0.01
            except Exception as exc:  # jax missing/broken → CPU-only context
                debug_verbose(2, "device", "TPU device unavailable: %s", exc)

    def add(self, dev: Device) -> Device:
        dev.attach(self, len(self.devices))
        self.devices.append(dev)
        debug_verbose(4, "device", "registered device %d: %s",
                      dev.index, dev.name)
        return dev

    def device_for(self, device_type: DeviceType, task: Task) -> Optional[Device]:
        """parsec_get_best_device analog: among devices matching the chore's
        type, pick the least (load / weight); ties go to the heavier device
        (idle accelerator beats idle CPU). The recursive pseudo-device is
        never auto-selected — only chores that name it explicitly use it
        (reference: PARSEC_DEV_RECURSIVE is special-cased in the core, not
        part of load balancing)."""
        best, best_score = None, None
        for dev in self.devices:
            if not (dev.device_type & device_type):
                continue
            if dev.device_type == DeviceType.RECURSIVE and \
                    device_type != DeviceType.RECURSIVE:
                continue
            # (load+1)/weight, not load/weight: an IDLE low-weight
            # device must not win ties against an accelerator whose
            # manager holds queued work (a 0.01-weight CPU device then
            # only wins when the accelerator is ~10000 deep)
            score = (dev.load + 1.0) / dev.weight
            if best_score is None or score < best_score or \
                    (score == best_score and dev.weight > best.weight):
                best, best_score = dev, score
        if best is not None:
            with best._lock:
                best.load += 1.0       # in-flight unit; the context
        return best                    # releases it (see release_load)

    def by_type(self, device_type: DeviceType) -> List[Device]:
        return [d for d in self.devices if d.device_type & device_type]

    def dump_statistics(self) -> List[Dict]:
        """parsec_mca_device_dump_and_reset_statistics analog."""
        return [d.dump_statistics() for d in self.devices]
