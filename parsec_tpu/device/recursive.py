"""Recursive device: run a nested taskpool inside a task.

Reference: PARSEC_DEV_RECURSIVE (device.h:64) — a chore of type RECURSIVE
builds a child taskpool (e.g. a finer-tiled factorization of one tile) and
the task completes when the child terminates. The chore hook must return a
``Taskpool``; the parent task's completion is deferred until the child
taskpool's on_complete fires (HookReturn.ASYNC path).
"""

from __future__ import annotations

from .base import Device
from ..core.task import Chore, DeviceType, HookReturn, Task
from ..core.taskpool import Taskpool


class RecursiveDevice(Device):
    device_type = DeviceType.RECURSIVE
    name = "recursive"

    def execute(self, es, task: Task, chore: Chore) -> HookReturn:
        # exceptions below propagate with rc unset → the context's
        # finally releases the in-flight unit; on the successful ASYNC
        # return we release here, as soon as the child is enqueued,
        # rather than holding the slot for the child's whole runtime
        child = chore.hook(task, *task.input_values())
        if not isinstance(child, Taskpool):
            raise TypeError("recursive chore must return a Taskpool")
        self.release_load()
        ctx = self.registry.context

        def _child_done(tp, _task=task) -> None:
            if tp.error is not None:
                # child failed: propagate instead of completing the parent
                # as a success with empty outputs
                _task.taskpool.abort(tp.error)
                return
            ctx.complete_task(None, _task)

        child.on_complete = _child_done
        ctx.add_taskpool(child)
        return HookReturn.ASYNC
