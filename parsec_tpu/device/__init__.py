"""Device framework (reference parsec/mca/device/).

The reference registers device modules (CPU, recursive, CUDA) with
per-device statistics and GFLOPS weights used for load balancing
(device.c:194-906, parsec_get_best_device device.c:79). Here:

- :class:`CPUDevice` executes chores inline on the worker thread (numpy /
  plain Python bodies).
- :class:`TPUDevice` (device/tpu.py) executes chores through JAX: bodies
  are jnp/pallas functions jitted per task class; XLA's async dispatch
  plays the role of the reference's stream pipeline — the returned arrays
  are futures, so successive tasks pipeline on-chip without host sync.
- :class:`RecursiveDevice` runs a nested taskpool inside a task
  (PARSEC_DEV_RECURSIVE, device.h:64).
"""

from .base import Device, Registry
from .cpu import CPUDevice
from .recursive import RecursiveDevice
from ..core.task import DeviceType

__all__ = ["Device", "Registry", "CPUDevice", "RecursiveDevice", "DeviceType"]
