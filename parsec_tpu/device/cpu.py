"""CPU device: executes chores inline on the worker thread."""

from __future__ import annotations

from .base import Device
from ..core.task import Chore, DeviceType, HookReturn, Task


class CPUDevice(Device):
    device_type = DeviceType.CPU
    name = "cpu"

    def execute(self, es, task: Task, chore: Chore) -> HookReturn:
        return self._run_hook(task, chore)
