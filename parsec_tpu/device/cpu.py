"""CPU device: executes chores inline on the worker thread."""

from __future__ import annotations

import sys

from .base import Device
from ..core.task import Chore, DeviceType, HookReturn, Task


class CPUDevice(Device):
    device_type = DeviceType.CPU
    name = "cpu"

    def execute(self, es, task: Task, chore: Chore) -> HookReturn:
        self._reconcile_devices(task)
        return self._run_hook(task, chore)

    @staticmethod
    def _reconcile_devices(task: Task) -> None:
        """Inputs produced by different accelerator modules arrive
        committed to different devices; eager jnp ops on such a mix
        raise ("incompatible devices"). Re-commit every jax input onto
        the FIRST jax input's device so the body sees one consistent
        placement (device_put is a no-op for already-resident
        buffers)."""
        if not task.data or "jax" not in sys.modules:
            return
        import jax
        target = None
        arrays = []
        for name, v in task.data.items():
            if isinstance(v, jax.Array):
                dev = getattr(v, "device", None)
                if target is None:
                    target = dev
                elif dev is not None and dev != target:
                    arrays.append(name)
        for name in arrays:
            task.data[name] = jax.device_put(task.data[name], target)
