"""Ex08: the dataflow hazard checker on intentionally-broken taskpools.

Seeds two classic PTG bugs — an unordered-writers race and a dependency
cycle — and shows `taskpool.validate()` catching both statically, before
a single task runs (the racy pool would finish with a schedule-dependent
tile value; the cyclic pool would hang forever).

Run:  python examples/ex08_lint_hazards.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.analysis import HazardError
from parsec_tpu.data import LocalCollection
from parsec_tpu.dsl import ptg


def build_racy() -> ptg.Taskpool:
    """W1(0) and W2(0) both write tile S(0,) with no edge between them:
    whichever completes last wins — a WAW hazard."""
    S = LocalCollection("S", {(0,): 0.0})
    tp = ptg.Taskpool("racy", S=S)
    for name, delta in (("W1", 1.0), ("W2", 10.0)):
        W = tp.task_class(
            name, params=("i",), space=lambda g: ((0,),),
            flows=[ptg.FlowSpec(
                "X", ptg.RW,
                ins=[ptg.In(data=lambda g, i: (g.S, (0,)))],
                outs=[ptg.Out(data=lambda g, i: (g.S, (0,)))])])

        @W.body
        def body(task, x, _d=delta):
            return x + _d
    return tp


def build_cyclic() -> ptg.Taskpool:
    """P(0) waits on Q(0) which waits on P(0): neither can ever start."""
    S = LocalCollection("S", {(0,): 0.0})
    tp = ptg.Taskpool("cyclic", S=S)
    tp.task_class(
        "P", params=("i",), space=lambda g: ((0,),),
        flows=[ptg.FlowSpec(
            "X", ptg.RW,
            ins=[ptg.In(src=("Q", lambda g, i: (i,), "Y"))],
            outs=[ptg.Out(dst=("Q", lambda g, i: (i,), "Y"))])])
    tp.task_class(
        "Q", params=("i",), space=lambda g: ((0,),),
        flows=[ptg.FlowSpec(
            "Y", ptg.RW,
            ins=[ptg.In(src=("P", lambda g, i: (i,), "X"))],
            outs=[ptg.Out(dst=("P", lambda g, i: (i,), "X"))])])
    return tp


def main() -> None:
    for builder in (build_racy, build_cyclic):
        tp = builder()
        print(f"--- {tp.name} ---")
        report = tp.validate(mode="warn")   # lint, log, don't raise
        for f in report.findings:
            print(f"  {f}")
        try:
            tp.validate(mode="error")
        except HazardError:
            print(f"  validate(mode='error') raised HazardError — "
                  f"{tp.name} would be refused at registration with "
                  f"--mca analysis.lint error")
        # the DOT report marks the hazard edges in red
        dot = report.to_dot()
        path = f"/tmp/{tp.name}.dot"
        with open(path, "w") as fh:
            fh.write(dot)
        print(f"  visual report: {path}")


if __name__ == "__main__":
    main()
