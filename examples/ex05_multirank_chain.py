"""Ex05: a chain crossing rank boundaries over the loopback fabric.

Reference: examples/Ex04 (MPI chain) — the same chain as Ex02, with
tiles owner-placed on alternating ranks; every hop is a remote
activation through the comm engine, and termination is detected by the
distributed four-counter wave.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import parsec_tpu as parsec
from parsec_tpu.comm.local import LocalCommEngine
from parsec_tpu.data import LocalCollection
from parsec_tpu.termdet import FourCounterTermdet
from ex02_chain import build_chain


def main():
    nranks, n = 2, 12
    engines = LocalCommEngine.make_fabric(nranks)
    ctxs, stores = [], []
    for r in range(nranks):
        ctx = parsec.init(nb_cores=2, comm=engines[r])
        # the single logical tile lives on rank 0 (LocalCollection's
        # default); task placement alternates via the affinity override
        # below, so every hop crosses ranks
        store = LocalCollection("S")
        store.write_tile(("x",), 0)

        # place T(i) on rank i % nranks: override the taskpool affinity
        tp = build_chain(store, n)
        tc = tp.get_task_class("T")
        tc.affinity_rank = lambda locals: locals[0] % nranks
        tp.monitor = FourCounterTermdet(comm=engines[r])
        ctxs.append(ctx)
        stores.append(store)
        ctx.add_taskpool(tp)
    for ctx in ctxs:
        ctx.start()
    for ctx in ctxs:
        ctx.wait()
    final_rank = (n - 1) % nranks
    print(f"{nranks}-rank chain of {n}: final value "
          f"{stores[final_rank].data_of(('x',))} on rank {final_rank}")
    for ctx in ctxs:
        parsec.fini(ctx)


if __name__ == "__main__":
    main()
