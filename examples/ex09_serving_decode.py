"""Ex09: multi-tenant serving — continuous-batching decode under faults.

A persistent Context in serving mode shared by four tenants:

- ``gold`` (weight 4) and ``free`` (weight 1): well-behaved decode
  tenants driving continuous-batching transformer decode loops —
  per-request decode steps are DTD insertions; the weighted-fair
  scheduler (``sched=wfq``) arbitrates between their pools.
- ``chaos``: submits requests whose task bodies raise — the first
  poison body quarantines the tenant; its later submissions are
  refused while the others keep serving.
- ``slow``: submits a pool with a 200 ms deadline that cannot finish —
  the reaper cancels it (queued tasks dropped, reservations released)
  without touching anyone else.

One gold request uses a LONG prompt whose prefill attention runs as a
single compiled ring-attention call over the virtual 8-device mesh
(``compiled/ring_attention.py``).

Run:  python examples/ex09_serving_decode.py
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np

import parsec_tpu as parsec
from parsec_tpu import serving
from parsec_tpu.data import LocalCollection
from parsec_tpu.dsl import dtd
from parsec_tpu.serving.decode import DecodeConfig, DecodeEngine
from parsec_tpu.serving.runtime import (AdmissionRejected,
                                        DeadlineExceeded,
                                        TenantQuarantined)
from parsec_tpu.utils import mca_param


def main():
    mca_param.set("sched", "wfq")          # weighted-fair across pools
    mca_param.set("pins", "tenant")        # per-tenant service accounting
    ctx = parsec.init(nb_cores=4, argv=sys.argv[1:])
    rt = serving.enable(ctx)
    ctx.start()

    gold = rt.tenant("gold", weight=4.0)
    free = rt.tenant("free", weight=1.0)
    chaos = rt.tenant("chaos", weight=0.5)

    cfg = DecodeConfig(d_model=32, n_heads=2, kv_tile=8)
    e_gold = DecodeEngine(ctx, "gold", cfg=cfg, tenant=gold).start()
    e_free = DecodeEngine(ctx, "free", cfg=cfg, tenant=free).start()
    e_chaos = DecodeEngine(ctx, "chaos", cfg=cfg, tenant=chaos).start()

    # long-context request: the prompt's attention is ONE compiled
    # ring-attention call over the 8-device mesh
    try:
        import jax
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
        e_gold.request(1000, 12, prompt_len=64, mesh=mesh)
        print("[prefill] 64-token prompt prefilled via ring attention "
              "on an 8-device mesh")
    except Exception as exc:  # noqa: BLE001 — demo survives without mesh
        print(f"[prefill] ring prefill unavailable ({exc}); dense path")
        e_gold.request(1000, 12)

    # mixed open-loop load + one poison request
    for rid in range(8):
        e_gold.request(rid, 10)
        e_free.request(rid, 10)
    e_chaos.request(0, 6, poison_at=2)

    # a doomed submission with a deadline
    slow_store = LocalCollection("slow", {(i,): 0.0 for i in range(32)})
    slow_tp = dtd.Taskpool("slow_job")
    slow_sub = ctx.submit(slow_tp, tenant="slow", deadline_s=0.2)
    gate = threading.Event()
    slow_tp.insert_tasks(lambda x: gate.wait(5.0) or x,
                         [[dtd.TileArg(slow_store, (i,), dtd.INOUT)]
                          for i in range(32)])

    done_gold = e_gold.drain(30.0)
    done_free = e_free.drain(30.0)
    print(f"[serve] gold: {len(done_gold)} requests, all bitwise-ok="
          f"{all(e_gold.verify(r) for r in done_gold)}")
    print(f"[serve] free: {len(done_free)} requests, all bitwise-ok="
          f"{all(e_free.verify(r) for r in done_free)}")

    time.sleep(0.2)     # let the poison land + the reaper fire
    try:
        slow_sub.wait(timeout=5.0)
    except DeadlineExceeded as exc:
        print(f"[deadline] {exc}")
    gate.set()

    print(f"[quarantine] chaos quarantined: "
          f"{chaos.quarantined is not None}")
    try:
        DecodeEngine(ctx, "chaos2", cfg=cfg, tenant=chaos).start()
    except TenantQuarantined as exc:
        print(f"[quarantine] resubmit refused: {str(exc)[:70]}...")

    # overload shedding: flood the queue past a tiny watermark, then a
    # low-weight submission is shed
    mca_param.set("serving.shed_watermark", 16)
    flood_store = LocalCollection("fl", {(i,): 0.0 for i in range(64)})
    flood = dtd.Taskpool("flood")
    ctx.submit(flood, tenant=gold)
    fgate = threading.Event()
    flood.insert_tasks(lambda x: fgate.wait(5.0) or x,
                       [[dtd.TileArg(flood_store, (i,), dtd.INOUT)]
                        for i in range(64)])
    try:
        ctx.submit(dtd.Taskpool("shed_me"), tenant=free)
    except AdmissionRejected as exc:
        print(f"[shed] {str(exc)[:80]}...")
    fgate.set()
    flood.wait()
    mca_param.unset("serving.shed_watermark")

    rep = rt.report()
    print("[report] runtime:", rep["stats"])
    mod = next(m for m in ctx.pins_modules if m.name == "tenant")
    for ten, row in sorted(mod.report()["tenants"].items()):
        print(f"[report] tenant {ten}: {row}")
    parsec.fini(ctx)


if __name__ == "__main__":
    main()
