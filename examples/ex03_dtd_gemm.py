"""Ex03: dynamic task discovery — tiled GEMM inserted at runtime.

Reference: the DTD taskpool examples (interfaces/dtd usage in
tests/dsl/dtd) — tasks are discovered by executing the insertion loop;
per-tile last-writer tracking builds the same DAG the PTG description
would.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import parsec_tpu as parsec
from parsec_tpu.algorithms import insert_gemm_dtd
from parsec_tpu.data import TiledMatrix
from parsec_tpu.dsl import dtd


def main():
    n, nb = 256, 64
    rng = np.random.default_rng(0)
    A_h = rng.standard_normal((n, n)).astype(np.float32)
    B_h = rng.standard_normal((n, n)).astype(np.float32)

    ctx = parsec.init(argv=sys.argv[1:])
    ctx.start()
    A = TiledMatrix.from_array(A_h, nb, nb, name="A")
    B = TiledMatrix.from_array(B_h, nb, nb, name="B")
    C = TiledMatrix.from_array(np.zeros((n, n), np.float32), nb, nb,
                               name="C")
    tp = dtd.Taskpool("gemm")
    ctx.add_taskpool(tp)
    insert_gemm_dtd(tp, A, B, C)
    tp.flush()
    tp.wait()
    err = np.linalg.norm(C.to_array() - A_h @ B_h) / np.linalg.norm(A_h @ B_h)
    print(f"DTD tiled GEMM {n}x{n} (nb={nb}): rel err {err:.2e}")
    parsec.fini(ctx)


if __name__ == "__main__":
    main()
