"""Ex06: the panel-fused dense factorization trio (POTRF/GEQRF/GETRF).

The flagship execution path: a left-looking taskpool (CTL-gather fan-in
concentrating each tile's updates) lowered by the PanelExecutor onto
Aᵀ-dense storage, so every trailing update is one or two large MXU
matmuls. Run with JAX_PLATFORMS=cpu for a quick local check or on a TPU
for real throughput.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from parsec_tpu.algorithms.geqrf import build_geqrf_hh
from parsec_tpu.algorithms.getrf import build_getrf_left
from parsec_tpu.algorithms.potrf import build_potrf_left
from parsec_tpu.compiled.panels import PanelExecutor
from parsec_tpu.compiled.wavefront import plan_taskpool
from parsec_tpu.data import TiledMatrix
from parsec_tpu.utils import mca_param

# Compile-once serving: the jit.cache_dir knob auto-enables the
# persistent compile caches (XLA cache + serialized executors under
# .xla_cache/executors) — re-running this example pays zero XLA
# compiles for the already-served shapes. PARSEC_COMPILE_CACHE=0
# disables both layers.
mca_param.set("jit.cache_dir", "auto")


def main():
    rng = np.random.default_rng(0)
    n, nb = 256, 64

    # POTRF: SPD input, result is L (lower) with Lᵀ scribble above.
    # segmented=True uses the compile-once serving path (bucketed
    # per-wave kernels, reused across N and across processes); the
    # default whole-DAG form is the fastest steady-state runtime.
    M = rng.standard_normal((n, n))
    spd = (M @ M.T + n * np.eye(n)).astype(np.float32)
    A = TiledMatrix.from_array(spd.copy(), nb, nb, name="A")
    PanelExecutor(plan_taskpool(build_potrf_left(A))).run(segmented=True)
    L = np.tril(A.to_array().astype(np.float64))
    print("potrf  residual:",
          np.linalg.norm(L @ L.T - spd) / np.linalg.norm(spd))

    # GEQRF: any matrix, result is R (upper) + zeros below
    G = rng.standard_normal((n, n)).astype(np.float32)
    B = TiledMatrix.from_array(G.copy(), nb, nb, name="B")
    PanelExecutor(plan_taskpool(build_geqrf_hh(B))).run()
    R = B.to_array().astype(np.float64)
    GtG = G.astype(np.float64).T @ G
    print("geqrf  residual:",
          np.linalg.norm(R.T @ R - GtG) / np.linalg.norm(GtG))

    # GETRF: diagonally dominant (no-pivot contract), packed L\\U result
    D = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(np.float32)
    C = TiledMatrix.from_array(D.copy(), nb, nb, name="C")
    PanelExecutor(plan_taskpool(build_getrf_left(C))).run()
    P = C.to_array().astype(np.float64)
    Lu = np.tril(P, -1) + np.eye(n)
    U = np.triu(P)
    print("getrf  residual:",
          np.linalg.norm(Lu @ U - D) / np.linalg.norm(D))


if __name__ == "__main__":
    main()
