"""Ex02: a dependency chain — T(i) feeds T(i+1).

Reference: examples/Ex02_Chain.jdf — the minimal dataflow: one task
class whose instances form a chain through a single RW flow.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import parsec_tpu as parsec
from parsec_tpu.data import LocalCollection
from parsec_tpu.dsl import ptg


def build_chain(store, n):
    tp = ptg.Taskpool("chain", N=n, S=store)
    T = tp.task_class(
        "T", params=("i",),
        space=lambda g: ((i,) for i in range(g.N)),
        flows=[ptg.FlowSpec(
            "X", ptg.RW,
            tile=lambda g, i: (g.S, ("x",)),
            ins=[ptg.In(data=lambda g, i: (g.S, ("x",)),
                        guard=lambda g, i: i == 0),
                 ptg.In(src=("T", lambda g, i: (i - 1,), "X"),
                        guard=lambda g, i: i > 0)],
            outs=[ptg.Out(dst=("T", lambda g, i: (i + 1,), "X"),
                          guard=lambda g, i: i < g.N - 1),
                  ptg.Out(data=lambda g, i: (g.S, ("x",)),
                          guard=lambda g, i: i == g.N - 1)])])

    @T.body
    def step(task, x):
        return x + 1

    return tp


def main():
    n = 20
    ctx = parsec.init(argv=sys.argv[1:])
    ctx.start()
    store = LocalCollection("S", {("x",): 0})
    ctx.add_taskpool(build_chain(store, n))
    ctx.wait()
    print(f"chain of {n}: final value {store.data_of(('x',))}")
    parsec.fini(ctx)


if __name__ == "__main__":
    main()
