"""Ex04: the compiled path — tiled Cholesky as ONE XLA program.

The TPU-idiomatic execution of a task DAG: plan_taskpool levels the
closed-form PTG DAG into waves, the executor batches same-class tasks
into vmapped calls over stacked HBM tile stores, and jax.jit fuses the
whole schedule. Compare with running the same taskpool on the host
runtime (Ex02-style dynamic scheduling).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import parsec_tpu as parsec
from parsec_tpu.algorithms import build_potrf
from parsec_tpu.algorithms.potrf import potrf_flops
from parsec_tpu.compiled import WavefrontExecutor, plan_taskpool
from parsec_tpu.data import TiledMatrix
from parsec_tpu.utils import mca_param

# Compile-once serving: persistent compile caches on (see ex06 /
# README "Compile-once serving"); a re-run of this example deserializes
# instead of re-compiling the whole-DAG program.
mca_param.set("jit.cache_dir", "auto")


def main():
    n, nb = 1024, 128
    rng = np.random.default_rng(0)
    M = rng.standard_normal((n, n))
    A_h = (M @ M.T + n * np.eye(n)).astype(np.float32)

    A = TiledMatrix.from_array(A_h.copy(), nb, nb, name="A")
    plan = plan_taskpool(build_potrf(A))
    print(f"planned: {plan.n_tasks} tasks in {plan.n_waves} waves")
    ex = WavefrontExecutor(plan)
    dt = ex.run()                    # compile + run + write back
    L = np.tril(A.to_array())
    err = np.linalg.norm(L @ L.T - A_h) / np.linalg.norm(A_h)
    print(f"POTRF {n} (nb={nb}): {potrf_flops(n)/dt/1e9:.1f} GF/s "
          f"(incl. compile), rel err {err:.1e}")


if __name__ == "__main__":
    main()
