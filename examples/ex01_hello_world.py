"""Ex01: hello world — one task, no dependencies.

Reference: examples/Ex00_StartStop.c + Ex01_HelloWorld.c — init the
runtime, run a single task, tear down.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import parsec_tpu as parsec
from parsec_tpu.data import LocalCollection
from parsec_tpu.dsl import ptg


def main():
    ctx = parsec.init(argv=sys.argv[1:])
    ctx.start()

    S = LocalCollection("S", {("msg",): "hello"})
    tp = ptg.Taskpool("hello", S=S)
    T = tp.task_class(
        "HELLO", params=("i",), space=lambda g: ((0,),),
        flows=[ptg.FlowSpec(
            "X", ptg.RW,
            ins=[ptg.In(data=lambda g, i: (g.S, ("msg",)))],
            outs=[ptg.Out(data=lambda g, i: (g.S, ("msg",)))])])

    @T.body_cpu
    def hello(task, x):
        print(f"{x} world from task {task!r}")
        return x + " world"

    ctx.add_taskpool(tp)
    ctx.wait()
    assert S.data_of(("msg",)) == "hello world"
    parsec.fini(ctx)
    print("done")


if __name__ == "__main__":
    main()
