"""Ex07: observability + runtime knobs — PINS counters, trace export,
the batching manager, and the THREAD_MULTIPLE comm option.

Shows the round-4 surfaces working together on a DTD GEMM:
- ``pins=counters`` (the pins/papi analog): per-task-class rusage/wall
  deltas sampled at EXEC begin/end;
- ``Trace`` with Chrome-trace export (open the JSON in Perfetto);
- ``device.tpu.batch_dispatch=1``: the per-device manager thread
  batches same-signature pure DTD bodies into one vmapped dispatch;
- ``comm.thread_multiple`` is a knob of the multi-process socket engine
  (see tests/test_socket_comm.py for 2-rank runs) — single-process runs
  here, so it is only printed, not exercised.

Run with JAX_PLATFORMS=cpu for a quick local check.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import parsec_tpu as parsec
    from parsec_tpu import dtd
    from parsec_tpu.algorithms import insert_gemm_dtd
    from parsec_tpu.data import TiledMatrix
    from parsec_tpu.profiling import Counters, Trace
    from parsec_tpu.utils import mca_param

    n, nb = 256, 64
    rng = np.random.default_rng(0)
    A_h = rng.standard_normal((n, n)).astype(np.float32)
    B_h = rng.standard_normal((n, n)).astype(np.float32)

    mca_param.set("device.tpu.batch_dispatch", 1)   # manager batching
    try:
        ctx = parsec.init(nb_cores=2)
        counters = Counters().install(ctx)
        trace = Trace().install(ctx)
        ctx.start()

        A = TiledMatrix.from_array(A_h, nb, nb, name="A")
        B = TiledMatrix.from_array(B_h, nb, nb, name="B")
        C = TiledMatrix.from_array(np.zeros((n, n), np.float32), nb, nb,
                                   name="C")
        tp = dtd.Taskpool("gemm")
        ctx.add_taskpool(tp)
        insert_gemm_dtd(tp, A, B, C)
        tp.wait()

        ref = A_h @ B_h
        err = np.abs(C.to_array() - ref).max() / np.abs(ref).max()
        assert err < 1e-2, f"GEMM wrong through batch_dispatch: {err:.2e}"
        print(f"GEMM ok, rel err {err:.2e}")

        # NOTE the interaction: under batch_dispatch tasks complete
        # ASYNC on the manager thread, so the per-thread rusage deltas
        # are skipped (cross-thread guard) and counted as async_tasks —
        # only wall time is cross-thread meaningful. Run with the knob
        # off to see utime/minflt populate.
        print("\npins/counters (papi analog) per task class:")
        for cls, tot in counters.report().items():
            print(f"  {cls}: tasks={int(tot['tasks'])} "
                  f"wall={tot['wall_s']*1e3:.1f}ms "
                  f"async={int(tot.get('async_tasks', 0))} "
                  f"utime={tot.get('utime_s', 0)*1e3:.1f}ms "
                  f"minflt={int(tot.get('minflt', 0))}")

        stats = [d.dump_statistics() for d in ctx.devices.devices
                 if d.name.startswith("tpu")]
        batched = sum(s.get("batched_tasks", 0) for s in stats)
        batches = sum(s.get("batches", 0) for s in stats)
        print(f"\nbatching manager: {batched} tasks in {batches} "
              f"vmapped batches")

        out = os.path.join(tempfile.gettempdir(), "ex07_trace.json")
        trace.dump_chrome_trace(out)
        print(f"Chrome trace written to {out} (open in Perfetto)")

        # ISSUE 9: the always-on metrics plane — Prometheus text +
        # JSON statusz, no listener needed (set --mca
        # serving.metrics_port 9100 for the HTTP /metrics + /statusz)
        print("\n/metrics excerpt:")
        for line in ctx.metrics_text().splitlines():
            if line.startswith(("parsec_tasks_completed_total",
                                "parsec_sched_ready_tasks")):
                print(" ", line)
        sz = ctx.statusz()
        print(f"statusz: scheduler={sz['scheduler']} "
              f"streams={len(sz['streams'])} "
              f"metric_families={len(sz['metrics'])}")
        # request tracing: submissions through Context.submit mint a
        # rid; `python -m parsec_tpu.profiling.tools critpath <rid>
        # rank*.json` prints the admission/queue/exec/wire breakdown
        print(f"\ncomm.thread_multiple = "
              f"{mca_param.get('comm.thread_multiple', 0)} "
              "(socket-engine knob; see tests/test_socket_comm.py)")

        counters.uninstall()
        parsec.fini(ctx)
    finally:
        mca_param.unset("device.tpu.batch_dispatch")


if __name__ == "__main__":
    main()
